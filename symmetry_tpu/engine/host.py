"""Engine host process: the TPU engine behind a pipe.

Why a separate process: the engine thread's JAX calls (dispatch and
device→host syncs over the TPU runtime) hold the GIL for long stretches.
In-process, that starves the provider's asyncio loop — measured in the
round-3 e2e bench as every client's TTFT collapsing to the wall time
(token events only flushed when the engine went idle). The reference
never hits this because its "engine" is an external HTTP server
(reference: src/provider.ts:210-214); this host process is our native
equivalent of that isolation, with a pipe instead of HTTP.

Roles (tpu.role, engine/disagg/): "unified" (default) serves the full
request; "prefill" builds each prompt's KV and emits it as a versioned
handoff frame instead of decoding; "decode" accepts `adopt` commands
carrying those frames, seeds its prefix store from them, and generates.
The disagg broker in the tpu_native backend runs a prefill+decode host
pair and pipes handoff → adopt between them.

Protocol: JSON lines.
  stdin  ← {"op": "submit", "id", "messages", "max_new", "sampling": {…},
            "speculative": bool?,   (optional per-request opt-out of
            speculative decoding; ignored unless tpu.speculative is on)
            "trace": str?,          (request trace id, threaded into
            scheduler spans so the request correlates across processes)
            "deadline_s": float?}   (seconds of end-to-end deadline left
            at submit; the scheduler sheds the request at admission with
            finish_reason "expired" if it has already passed)
           {"op": "cancel", "id"}
           {"op": "adopt", "id", "frame": base64 handoff frame,
            "max_new", "sampling", "speculative"?, "trace"?,
            "deadline_s"?}   (decode role only: adopt a handed-off KV
            prefix and resume the request; prompt tokens ride the frame,
            so no re-tokenization happens here)
           {"op": "clock", "t0": float}   (clock-offset handshake: the
            provider brackets our CLOCK_MONOTONIC read with its own —
            the NTP midpoint replaces the old assume-zero-offset policy)
           {"op": "trace"}   (span-ring snapshot for the Perfetto export)
           {"op": "metrics"}   (metrics-registry snapshot probe: the
            reply carries this process's utils/metrics.py families —
            the provider merges them tier-labeled into its Prometheus
            exposition and the peer-wire metrics reply)
           {"op": "profile", "duration_s": float?, "dir": str?}
            (on-demand jax.profiler capture, utils/devprof.py: runs a
            bounded device trace on its OWN thread — the serve loop
            and every stream keep flowing — and replies when done)
           {"op": "stats"} | {"op": "shutdown"}
  stdout → {"op": "ready", "model": …}            (after warmup)
           {"op": "clock", "t0", "t": our monotonic at receipt}
           {"op": "trace", "clock", "components": [{name, spans,
            counters, clock_offset_s}, …]}   (host + scheduler rings,
            stamps on THIS process's clock)
           {"op": "event", "id", "text", "done", "finish_reason",
            "error", "ttft_s", "tokens", "tokens_new",
            "t": {"recv", "picked", "first", "out"}}   ("t" on the
            FIRST event of a request only: per-stage CLOCK_MONOTONIC
            stamps — host recv, placement pick, first sampled token,
            pipe write — so the provider can attribute its TTFT)
           {"op": "events", "events": [{…event fields, no "op"…}, …]}
           {"op": "handoff", "id", "p", "prompt_len", "nbytes",
            "frame": base64}   (prefill role only: the finished prompt's
            aligned KV prefix, serialized; p == 0 is routing-only — the
            prompt was too short for an aligned prefix and the decode
            tier prefills it whole)
           {"op": "metrics", "role", "families": {…}}   (registry
            snapshot, utils/metrics.py shape)
           {"op": "profile", "path"} | {"op": "profile", "error"}
            (capture finished: the trace-artifact directory, or why
            the capture could not run — e.g. one already in progress)
           {"op": "stats", …}   (scheduler counters incl. deferred_depth,
            prefill_jobs_active, the prefix_cache hit/miss/evict/bytes
            block when the shared-prefix KV cache is enabled, and the
            speculative drafted/accepted/acceptance-rate block when
            tpu.speculative is on)

The batched `events` frame is the hot path: the scheduler coalesces each
decode block's per-slot deltas (plus any finishes and admission errors
from the same block) into ONE frame — one json.dumps, one pipe write,
one flush per block, instead of one per slot per block. Events inside a
frame are ordered; per-request order is the stream order. Single-event
flushes still go out as legacy `event` frames, so pre-batching consumers
keep working and the reader exercises both shapes; `ready`/`error`/
`stats` frames are always single. Emit-path counters (`pipe_writes`,
`pipe_event_writes`, `pipe_events`, `pipe_batched_frames`, `pipe_bytes`)
ride the stats reply under `emit` so the provider/bench can verify the
O(1)-writes-per-block contract end to end (`pipe_event_writes` is the
contract's numerator — ready/stats frames are not emit-path traffic).

Logs go to stderr. The host is intentionally synchronous: the scheduler's
block-boundary flush writes one line under a lock straight from the
engine thread — there is no latency-sensitive I/O in this process to
starve.

Run: python -m symmetry_tpu.engine.host <config.yaml>
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import TYPE_CHECKING, Any

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
from symmetry_tpu.protocol.keys import HostOp
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.utils.faults import FAULTS
from symmetry_tpu.utils.logging import logger, set_component
from symmetry_tpu.utils.metrics import METRICS, MetricName
from symmetry_tpu.utils.trace import Tracer

if TYPE_CHECKING:
    from symmetry_tpu.engine.scheduler import TokenEvent


# Raw-KV byte bound for one handoff frame. The frame travels the broker
# pipes as ONE base64 JSON line (~4/3 × raw), and the backend's
# StreamReader line limit in disagg mode is 1 GiB — a frame that
# overflows it kills the reader and crash-loops the supervised pair, so
# the prefill host must never emit one. Oversized prefixes are capped to
# the largest ALIGNED length that fits (KV at position i depends only on
# tokens <= i, so a shorter prefix is always sound — the decode tier
# just re-prefills a longer suffix).
HANDOFF_MAX_KV_BYTES = 384 * 1024 * 1024


class EngineHost:
    def __init__(self, config: ConfigManager) -> None:
        self._config = config
        # Fault injection (utils/faults.py): env SYMMETRY_FAULTS is
        # inherited from the provider and already loaded at import; a
        # provider-config `faults:` mapping rides the config file here.
        # (config is None in protocol unit tests that never start().)
        if config is not None:
            FAULTS.load(config.get("faults"))
        self._engine: InferenceEngine | None = None
        self._scheduler: Scheduler | None = None
        self._wlock = threading.Lock()
        self._cancelled: set[str] = set()
        self._reported: dict[str, int] = {}  # id -> tokens already reported
        # The host's OWN trace ring (the pipe/framing layer): per-request
        # submit spans (pipe read → tokenized → enqueued) and per-frame
        # flush spans. The scheduler's ring lives on the scheduler; the
        # `trace` op ships both.
        self.tracer = Tracer()
        # Emit-path counters (under _wlock): every stdout line counts one
        # pipe_write; pipe_event_writes counts only lines that carry
        # TokenEvents (the writes-per-block contract is about THESE —
        # ready/stats frames are not emit-path traffic); pipe_events
        # counts TokenEvents carried (== event writes only if nothing
        # coalesces). The O(1)-writes-per-block assertion in tests and
        # the bench emit metrics both read these.
        self.emit_stats = {"pipe_writes": 0, "pipe_event_writes": 0,
                           "pipe_events": 0, "pipe_batched_frames": 0,
                           "pipe_bytes": 0}
        # Disaggregation (engine/disagg/): the host's tier role and its
        # side of the handoff accounting — serialize wall + frame bytes
        # on the prefill tier, deserialize/adoption outcomes on the
        # decode tier. Both ride the stats op (→ provider → bench).
        self._role = (getattr(config.tpu, "role", "unified") or "unified"
                      if config is not None else "unified")
        self.handoff_stats = {"frames": 0, "bytes": 0, "prefix_tokens": 0,
                              "routing_only": 0, "serialize_s": 0.0,
                              # Block-manifest accounting (frames v2):
                              # blocks covered by emitted manifests vs
                              # blocks whose payload actually shipped —
                              # the gap is the incremental-handoff win
                              # (asserted by the disagg smoke's
                              # warm-handoff leg).
                              "blocks": 0, "blocks_shipped": 0}
        # Digests of blocks already shipped from this prefill host,
        # PER DESTINATION MEMBER (LRU-bounded per member). A block in a
        # member's ledger is OMITTED from later frames to that member:
        # it adopts the block by reference from its radix tree, or — if
        # it evicted the block since — shortens the adopted prefix and
        # re-prefills a longer suffix (correct either way; the ledger
        # is a bytes optimization, never a correctness input). The
        # submit op's "ledger" field names the planned destination and
        # its ledger EPOCH (bumped by the router every time that member
        # goes lost); an advanced epoch drops the member's entries —
        # its respawned cache is empty, and while skipping blocks it no
        # longer holds stays CORRECT (shorter adopted prefix), it would
        # silently degrade every warm handoff to a full re-prefill.
        # Submits without the field (the fixed pair, old providers)
        # book under one default key — pool-of-1 degenerates to the
        # pair semantics. Gated by tpu.handoff_ledger (default on).
        from collections import OrderedDict

        self._ledger_on = bool(getattr(config.tpu, "handoff_ledger",
                                       False)) if config is not None \
            else False
        self._shipped: dict[str, OrderedDict[str, None]] = {}
        self._shipped_cap = 65536          # digests kept per member
        self._ledger_epochs: dict[str, int] = {}
        self._ledger_dest: dict[str, str] = {}  # req id -> member key
        self.adopt_stats = {"frames": 0, "bytes": 0, "adopted": 0,
                            "rejected": 0, "errors": 0,
                            "deserialize_s": 0.0}
        # Always-on registry families (utils/metrics.py): this process's
        # slice of the fleet time series, shipped to the provider via the
        # HostOp.METRICS probe and tier-labeled there. `metrics.enabled:
        # false` in the provider config disables the whole registry (the
        # host reads the same config copy in start()).
        self._m_pipe_writes = METRICS.counter(
            MetricName.HOST_PIPE_WRITES, "host stdout frames written")
        self._m_pipe_bytes = METRICS.counter(
            MetricName.HOST_PIPE_BYTES, "host stdout bytes written")
        self._m_pipe_events = METRICS.counter(
            MetricName.HOST_PIPE_EVENTS, "token events carried on the pipe")
        self._m_handoff_frames = METRICS.counter(
            MetricName.HOST_HANDOFF_FRAMES,
            "handoff frames emitted (prefill role)")
        self._m_handoff_bytes = METRICS.counter(
            MetricName.HOST_HANDOFF_BYTES, "handoff frame bytes emitted")
        self._m_handoff_serialize = METRICS.histogram(
            MetricName.HOST_HANDOFF_SERIALIZE,
            "handoff extract+serialize wall per frame")
        self._m_adopt_frames = METRICS.counter(
            MetricName.HOST_ADOPT_FRAMES,
            "handoff frames processed by the decode role",
            labels=("outcome",))
        self._m_adopt_deserialize = METRICS.histogram(
            MetricName.HOST_ADOPT_DESERIALIZE,
            "handoff decode+validate+insert wall per frame")

    # ---------------------------------------------------------------- wire

    def _write(self, obj: dict[str, Any], *, events: int = 0) -> None:
        if FAULTS.enabled and FAULTS.point("host.pipe_write"):
            return  # injected drop_frame: the frame is lost on the wire
        line = json.dumps(obj, separators=(",", ":"))
        t0 = time.monotonic()
        with self._wlock:
            self.emit_stats["pipe_writes"] += 1
            self.emit_stats["pipe_events"] += events
            self.emit_stats["pipe_bytes"] += len(line) + 1
            if events > 0:
                self.emit_stats["pipe_event_writes"] += 1
            if events > 1:
                self.emit_stats["pipe_batched_frames"] += 1
            sys.stdout.write(line + "\n")
            sys.stdout.flush()
        self._m_pipe_writes.inc()
        self._m_pipe_bytes.inc(len(line) + 1)
        if events:
            self._m_pipe_events.inc(events)
        if events > 0:
            # Event frames only (one per block): the flush hold is the
            # "emit" leg of the TTFT chain, worth a span; ready/stats
            # frames are not emit-path traffic.
            self.tracer.record("pipe_flush", t0, time.monotonic() - t0,
                               events=events, bytes=len(line) + 1)

    def _event_dict(self, req_id: str, ev: "TokenEvent") -> dict[str, Any]:
        """One event's wire fields (shared by legacy and batched frames),
        with the per-request delta bookkeeping. tokens_new deltas ride
        tokens_emitted — only tokens that actually streamed as text, so
        summing them reproduces the bench's tokens_streamed exactly (the
        EOS token and post-finish block remainders are excluded; the
        cumulative `tokens` field keeps the EOS-counting convention)."""
        prev = self._reported.get(req_id, 0)
        new = max(ev.tokens_emitted - prev, 0)
        self._reported[req_id] = max(ev.tokens_emitted, prev)
        out: dict[str, Any] = {"id": req_id, "text": ev.text,
                               "tokens": ev.tokens_generated,
                               "tokens_new": new}
        if ev.ttft_s is not None:
            out["ttft_s"] = round(ev.ttft_s, 4)
        if ev.stages:
            # First event of the request: forward the scheduler's stage
            # stamps and add the pipe-write moment, so the provider can
            # attribute its TTFT per stage (host recv → pick → first
            # token → pipe out; all CLOCK_MONOTONIC, one clock across
            # processes on Linux).
            out["t"] = {k: round(v, 4) for k, v in ev.stages.items()
                        if v is not None}
            out["t"]["out"] = round(time.monotonic(), 4)
        if ev.tokens_reused is not None:
            # First-event rider: radix tokens the admission reused
            # (resume admissions assert > 0 — the cheap-resume contract).
            out["reused"] = ev.tokens_reused
        if ev.resumed_from is not None:
            # Resume continuation start offset, in the client's token
            # numbering — the relay drops any overlap below the client's
            # own count (offset dedup: a resume never replays tokens the
            # client already has).
            out["resume_from"] = ev.resumed_from
        if ev.done:
            out["done"] = True
            out["finish_reason"] = ev.finish_reason
            if ev.error:
                out["error"] = ev.error
            if ev.costs is not None:
                # symledger terminal rider (engine/ledger.py): the
                # request's attributed cost block rides its finish
                # event to the provider, which stamps it on the final
                # stream frame behind tpu.ledger.
                out["costs"] = ev.costs
            self._reported.pop(req_id, None)
            self._cancelled.discard(req_id)
        return out

    def _emit_batch(self, batch: list[tuple[GenRequest, "TokenEvent"]]
                    ) -> None:
        """Scheduler block-boundary sink: the whole block's events leave
        as ONE pipe write+flush. A lone event keeps the legacy single
        `event` frame (wire-compatible with pre-batching readers)."""
        events = [self._event_dict(req.id, ev) for req, ev in batch]
        if len(events) == 1:
            self._write({"op": HostOp.EVENT, **events[0]}, events=1)
        else:
            self._write({"op": HostOp.EVENTS, "events": events},
                        events=len(events))

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        import time

        from symmetry_tpu.utils.compile_cache import enable_compile_cache

        # Persistent XLA compile cache (round-3 verdict #4): without it
        # every host start recompiles the full serving grid (~90 s of the
        # observed 94 s startup); with it a config-identical restart
        # compiles ~nothing.
        cache_dir = enable_compile_cache(self._config.tpu)
        t0 = time.perf_counter()
        self._engine = InferenceEngine.from_tpu_config(self._config.tpu)
        t_build = time.perf_counter() - t0
        sched_engine = self._engine
        mh = self._config.tpu.multihost
        if mh and mh.get("num_processes", 1) > 1:
            # Rank 0 fronts the scheduler; its commands drive all ranks in
            # lockstep (parallel/multihost.py). Worker ranks run
            # `python -m symmetry_tpu.provider --worker` as before.
            from symmetry_tpu.parallel.multihost import (
                CommandLoop, MultihostEngine)

            self._command_loop = CommandLoop(self._engine,
                                             is_coordinator=True)
            sched_engine = MultihostEngine(self._command_loop)
        t1 = time.perf_counter()
        sched_engine.warmup()
        t_warmup = time.perf_counter() - t1
        self._scheduler = Scheduler(
            sched_engine, emit_batch=self._emit_batch,
            pipeline_depth=int(getattr(self._config.tpu,
                                       "pipeline_depth", 2)),
            handoff=(self._handoff_sink if self._role == "prefill"
                     else None),
            ledger_enabled=bool(getattr(self._config.tpu,
                                        "ledger", True)))
        # tpu.tracing=False empties every ring (the bench A/B knob); the
        # default leaves the bounded always-on recorder running.
        tracing = bool(getattr(self._config.tpu, "tracing", True))
        self.tracer.enabled = tracing
        self._scheduler.tracer.enabled = tracing
        # Metrics registry gate (metrics.enabled: false → every registry
        # op in this process is one branch) + the structured-log
        # component tag for this process's records.
        mcfg = self._config.get("metrics") or {}
        METRICS.enabled = bool(mcfg.get("enabled", True))
        set_component("host")
        self._scheduler.start()
        self._write({"op": HostOp.READY,
                     "model": self._config.model_name,
                     "role": self._role,
                     "slots": self._engine.max_slots,
                     "max_seq_len": self._engine.max_seq_len,
                     "build_s": round(t_build, 1),
                     "warmup_s": round(t_warmup, 1)})
        # Startup breakdown to stderr: a slow start must carry its own
        # explanation in the provider log (round-3 verdict #1).
        logger.info(f"engine host ready: model={self._config.model_name} "
                    f"role={self._role} slots={self._engine.max_slots} "
                    f"build={t_build:.1f}s warmup={t_warmup:.1f}s "
                    f"compile_cache={cache_dir or 'off'}")

    def serve_forever(self) -> int:
        self.start()
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            if FAULTS.enabled and FAULTS.point("host.pipe_read"):
                continue  # injected drop_frame: the command is lost
            try:
                msg = json.loads(line)
            except ValueError:
                logger.warning(f"host: bad command line {line[:80]!r}")
                continue
            op = msg.get("op")
            if op == HostOp.SUBMIT:
                self._submit(msg)
            elif op == HostOp.ADOPT:
                self._handle_adopt(msg)
            elif op == HostOp.CANCEL:
                req_id = str(msg.get("id", ""))
                if req_id in self._reported:  # only live requests; a late
                    self._cancelled.add(req_id)  # cancel must not leak ids
            elif op == HostOp.CLOCK:
                self._handle_clock(msg)
            elif op == HostOp.TRACE:
                self._handle_trace()
            elif op == HostOp.STATS:
                stats = getattr(self._scheduler, "stats", None)
                m = stats() if stats is not None else dict(
                    self._scheduler.metrics)
                m["op"] = HostOp.STATS
                # liveness of the engine thread — the wedged-decode-loop
                # signal the provider's health loop needs (SURVEY §5.3)
                thread = self._scheduler._thread
                m["engine_alive"] = bool(thread is not None
                                         and thread.is_alive())
                # Snapshot without _wlock — _write below takes it (non-
                # reentrant), and a dict-of-ints copy is GIL-atomic enough
                # for a stats read.
                m["emit"] = dict(self.emit_stats)
                m["role"] = self._role
                # Per-request emitted-token journal rider: the tokens
                # each live stream has had WRITTEN to the pipe. The
                # backend's supervisor keeps the last heartbeat's copy,
                # so a crash/wedge shed stamps an accurate `emitted`
                # count even for frames the relay never got to read —
                # the resume path's RNG-lane position. Tiny by
                # construction (one int per in-flight request). Listed
                # keys first: the engine thread mutates _reported
                # concurrently and iteration must not race a resize.
                m["journal"] = {k: self._reported.get(k, 0)
                                for k in list(self._reported)}
                # Pool-gossip rider: the engine's radix-cache summary
                # (hot-path block digests + depth histogram) rides every
                # stats reply — the provider's PoolRouter harvests it
                # off the heartbeat probe for cache-affine placement. A
                # payload field on an existing op, not a new op: the
                # wire contract (W101–W104) stays untouched, and members
                # that predate the field simply gossip nothing (the
                # router degrades to load-only for them).
                summary = getattr(self._engine, "prefix_cache_summary",
                                  None)
                if summary is not None:
                    ps = summary()
                    if ps is not None:
                        m["prefix_summary"] = ps
                if self._role == "prefill":
                    m["handoff"] = {**self.handoff_stats,
                                    "serialize_s": round(
                                        self.handoff_stats["serialize_s"],
                                        4)}
                elif self._role == "decode":
                    m["adopt"] = {**self.adopt_stats,
                                  "deserialize_s": round(
                                      self.adopt_stats["deserialize_s"],
                                      4)}
                if FAULTS.enabled:
                    # Armed-fault accounting: a chaos run's stats carry
                    # which seams fired, so the test/bench can assert the
                    # injection actually happened.
                    m["faults"] = FAULTS.counters()
                self._write(m)
            elif op == HostOp.METRICS:
                self._handle_metrics()
            elif op == HostOp.PROFILE:
                self._handle_profile(msg)
            elif op == HostOp.SHUTDOWN:
                break
        self._scheduler.stop()
        if getattr(self, "_command_loop", None) is not None:
            self._command_loop.stop()
        return 0

    def _handle_clock(self, msg: dict) -> None:
        """Clock-offset handshake: echo the provider's send stamp and add
        our CLOCK_MONOTONIC read. The provider brackets this read with its
        own stamps and takes the min-RTT NTP midpoint — the measured
        offset the per-stage TTFT attribution applies instead of clamping
        negative cross-process spans to zero."""
        self._write({"op": HostOp.CLOCK, "t0": msg.get("t0"),
                     "t": time.monotonic()})

    def _handle_metrics(self) -> None:
        """Metrics-registry snapshot: this process's families (compact —
        no recent-sample rings on the wire) plus the tier role, so the
        provider can merge them tier-labeled into its exposition."""
        snap = METRICS.snapshot(compact=True)
        self._write({"op": HostOp.METRICS, "role": self._role, **snap})

    def _handle_trace(self) -> None:
        """Span-ring snapshot: this process's host + scheduler rings,
        stamps on this process's clock (the provider adds its measured
        offset when merging), plus the symprof DEVICE track (probed
        per-kind device spans + dispatch gaps) when tpu.profile_sample
        is on — the device row that renders beside the request spans."""
        comps = [self.tracer.component("host")]
        trace_export = getattr(self._scheduler, "trace_export", None)
        if trace_export is not None:
            comps.append(trace_export())
        devprof = getattr(self._engine, "devprof", None)
        if devprof is not None and devprof.enabled:
            comps.append(devprof.component("device"))
        self._write({"op": HostOp.TRACE, "clock": time.monotonic(),
                     "components": comps})

    def _handle_profile(self, msg: dict) -> None:
        """On-demand jax.profiler capture (utils/devprof.py): the
        capture sleeps for its whole window, so it runs on its OWN
        daemon thread — the serve loop keeps reading commands and the
        engine keeps dispatching (the capture's entire point is to
        observe live traffic). The reply is written when the capture
        finishes; a concurrent capture request is refused loudly."""
        import tempfile

        from symmetry_tpu.utils.devprof import capture_device_profile

        # `is None`, not `or`: an explicit duration_s of 0 means the
        # minimal instant capture, not the 2 s default.
        raw = msg.get("duration_s")
        duration_s = 2.0 if raw is None else float(raw)
        out_dir = str(msg.get("dir") or "") or os.path.join(
            tempfile.gettempdir(), "symmetry_tpu_profiles")

        def run() -> None:
            try:
                path = capture_device_profile(out_dir, duration_s)
            except Exception as exc:  # noqa: BLE001 — reply, never crash
                self._write({"op": HostOp.PROFILE, "error": str(exc)})
                return
            logger.info(f"device profile captured → {path} "
                        f"({duration_s:.1f}s window)")
            self._write({"op": HostOp.PROFILE, "path": path,
                         "duration_s": duration_s})

        threading.Thread(target=run, name="jax-profile",
                         daemon=True).start()

    # --------------------------------------------------------------- submit

    def _submit(self, msg: dict) -> None:
        t_recv = time.monotonic()
        req_id = str(msg.get("id", ""))
        trace_id = str(msg.get("trace") or "")
        s = msg.get("sampling") or {}
        resume = msg.get("resume") if isinstance(msg.get("resume"), dict) \
            else None
        max_new = int(msg.get("max_new", 512))
        resume_offset = 0
        try:
            prompt_ids = self._engine.tokenizer.apply_chat_template(
                msg.get("messages") or [])
            # Stream resumption (resolve_resume, tokenizer.py — ONE
            # implementation across every admission path): condition on
            # prompt + the emitted text the client already holds,
            # generate only the continuation. The emitted run re-enters
            # through the ordinary admission path — prompt+emitted
            # blocks hit the radix cache (only the unaligned tail
            # re-prefills) and the seed path treats it like any other
            # prompt; the resolved offset positions a seeded request's
            # RNG lane and offsets the token budget.
            from symmetry_tpu.engine.tokenizer import resolve_resume

            prompt_ids, max_new, resume_offset = resolve_resume(
                self._engine.tokenizer, resume, prompt_ids, max_new)
        except Exception as exc:  # noqa: BLE001 — tokenizer failure → event
            self._write({"op": HostOp.EVENT, "id": req_id, "text": "",
                         "done": True, "finish_reason": "error",
                         "error": f"tokenization failed: {exc}"}, events=1)
            return
        if resume is not None and max_new == 0:
            # The interrupted stream had already spent the whole token
            # budget — only the finish frame was lost. Complete NOW
            # (finish "length", zero new tokens) instead of generating
            # past the client's max_tokens.
            self._write({"op": HostOp.EVENT, "id": req_id, "text": "",
                         "done": True, "finish_reason": "length",
                         "tokens": resume_offset, "tokens_new": 0,
                         "resume_from": resume_offset}, events=1)
            return
        sampling = SamplingParams(
            temperature=float(s.get("temperature", 0.0)),
            top_p=float(s.get("top_p", 1.0)),
            top_k=int(s.get("top_k", 0)),
            seed=s.get("seed"),
            rng_skip=resume_offset,
        )
        led = msg.get("ledger")
        if self._role == "prefill" and isinstance(led, dict):
            # Pool routing told us which decode member this request's
            # handoff is planned for, and that member's ledger epoch.
            # An advanced epoch means the member respawned since we
            # last shipped to it: drop its ledger NOW, before this
            # request's handoff would skip blocks an empty cache
            # cannot adopt by reference.
            member = str(led.get("member") or "decode")
            epoch = int(led.get("epoch") or 0)
            with self._wlock:
                if epoch > self._ledger_epochs.get(member, 0):
                    self._ledger_epochs[member] = epoch
                    self._shipped.pop(member, None)
                self._ledger_dest[req_id] = member
                while len(self._ledger_dest) > self._shipped_cap:
                    # Requests that end without a handoff (cancel,
                    # deadline shed) leave their entry behind; bound it.
                    self._ledger_dest.pop(next(iter(self._ledger_dest)))
        if self._role == "prefill":
            pb = self._engine.prefix_block or 0
            if pb and (len(prompt_ids) - 1) // pb == 0:
                # Short-prompt fast path: no whole-block prefix can be
                # handed off, so running the prefill HERE would only
                # duplicate the decode tier's suffix dispatch. Route the
                # tokens straight through as a routing-only frame — the
                # decode host prefills the whole (tiny) prompt itself.
                self._emit_handoff(req_id, prompt_ids, 0, None)
                return
        self._reported[req_id] = 0

        def emit(ev, req_id=req_id) -> None:
            # Fallback path only: the scheduler delivers through the
            # emit_batch sink; this fires if batching is ever disabled.
            self._write({"op": HostOp.EVENT, **self._event_dict(req_id, ev)},
                        events=1)

        spec = msg.get("speculative")
        deadline = msg.get("deadline_s")
        self._scheduler.submit(GenRequest(
            prompt_ids=prompt_ids, sampling=sampling,
            max_new_tokens=max_new,
            emit=emit,
            cancelled=lambda: req_id in self._cancelled,
            id=req_id,
            speculative=spec if isinstance(spec, bool) else None,
            trace_id=trace_id,
            resume_offset=resume_offset,
            # deadline_s is RELATIVE (seconds left at provider submit);
            # anchor it to this process's clock at receipt so the
            # scheduler's admission check needs no cross-process offset.
            deadline_at=(t_recv + float(deadline)
                         if deadline is not None else None)))
        # The pipe_in leg as a span: command read → tokenized → enqueued.
        self.tracer.record("host_submit", t_recv,
                           time.monotonic() - t_recv,
                           request_id=req_id, trace_id=trace_id,
                           prompt_len=len(prompt_ids))

    # -------------------------------------------------------------- disagg

    def _handoff_sink(self, slot: int, req: Any, first: int) -> None:
        """Prefill-role scheduler terminal (runs on the engine thread):
        snapshot the slot lane's KV through the whole-block prefix
        length, serialize it blockwise, and emit the handoff frame. By
        return the lane is free — the np.asarray below syncs the
        extract before the scheduler can reuse the slot."""
        import numpy as np

        t0 = time.monotonic()
        n = len(req.prompt_ids)
        pb = self._engine.prefix_block or 0
        p = pb * ((n - 1) // pb) if pb else 0
        if p > 0:
            # Pipe-transport bound: cap to the largest whole-block
            # prefix whose frame fits the broker's line limit (see
            # HANDOFF_MAX_KV_BYTES). Shorter-than-built prefixes are
            # causally sound; the decode tier pays a longer suffix.
            max_p = pb * (HANDOFF_MAX_KV_BYTES
                          // self._engine.kv_bytes_per_token() // pb)
            p = min(p, max_p)
        arrays = None
        if p > 0:
            cache = self._engine.extract_slot_kv(slot, p)
            # Slice to p positions host-side: the frame ships only the
            # prefix the decode tier will adopt, not the lane's full
            # capacity — handoff bytes scale with the prompt, not the
            # engine's max_seq_len.
            arrays = {"k": np.asarray(cache.k)[:, :, :p],
                      "v": np.asarray(cache.v)[:, :, :p]}
            if self._engine.kv_quant:
                arrays["k_scale"] = np.asarray(cache.k_scale)[:, :, :, :p]
                arrays["v_scale"] = np.asarray(cache.v_scale)[:, :, :, :p]
        self._emit_handoff(req.id, req.prompt_ids, p, arrays, t0=t0)

    def _emit_handoff(self, req_id: str, prompt_ids: list[int], p: int,
                      arrays: Any, t0: float | None = None) -> None:
        from symmetry_tpu.engine.disagg import encode_kv_handoff
        from symmetry_tpu.engine.prefix_cache import block_digests

        if t0 is None:
            t0 = time.monotonic()
        # disagg.handoff seam: crash = the prefill host dies with the
        # request's KV built but unshipped (the smoke's mid-request
        # failure); drop_frame = the frame is lost and the request
        # silently vanishes (watchdog territory).
        if FAULTS.enabled and FAULTS.point("disagg.handoff"):
            return
        pb = self._engine.prefix_block or 0
        skip: list[int] = []
        digests: list[str] = []
        with self._wlock:
            # _submit's pipe-reader thread writes this map; this method
            # runs on the engine thread too (symlint C202).
            member = self._ledger_dest.pop(req_id, "decode")
        if p > 0 and pb and self._ledger_on:
            # Incremental handoff: blocks whose digest this host already
            # shipped TO THIS DESTINATION are omitted from the payload
            # (manifest-only). The ledger mutates under _wlock — this
            # method runs on the engine thread AND the pipe-reader
            # thread (fast path).
            digests = block_digests(prompt_ids, p, pb)
            with self._wlock:
                ledger = self._shipped.get(member)
                if ledger is not None:
                    skip = [j for j, d in enumerate(digests)
                            if d in ledger]
        frame = encode_kv_handoff(req_id, prompt_ids, p, arrays,
                                  kv_quant=self._engine.kv_quant,
                                  block_size=pb, skip=skip,
                                  digests=digests if digests else None)
        import base64

        b64 = base64.b64encode(frame).decode("ascii")
        dt = time.monotonic() - t0
        n_blocks = p // pb if (p and pb) else 0
        # Under _wlock: this method runs on the ENGINE thread via the
        # scheduler's handoff sink AND on the pipe-reader thread via the
        # short-prompt fast path in _submit — unlocked `dict[k] += 1`
        # from two threads loses updates (symlint C202).
        with self._wlock:
            self.handoff_stats["frames"] += 1
            self.handoff_stats["bytes"] += len(frame)
            self.handoff_stats["prefix_tokens"] += p
            self.handoff_stats["blocks"] += n_blocks
            self.handoff_stats["blocks_shipped"] += n_blocks - len(skip)
            if p == 0:
                self.handoff_stats["routing_only"] += 1
            self.handoff_stats["serialize_s"] += dt
            if digests:
                from collections import OrderedDict

                ledger = self._shipped.setdefault(member, OrderedDict())
                for d in digests:
                    ledger.pop(d, None)
                    ledger[d] = None  # most-recently-shipped last
                while len(ledger) > self._shipped_cap:
                    ledger.popitem(last=False)
        self._m_handoff_frames.inc()
        self._m_handoff_bytes.inc(len(frame))
        self._m_handoff_serialize.observe(dt)
        # This host's bookkeeping for the request ends here: token
        # events (and any cancel) now belong to the decode tier.
        self._reported.pop(req_id, None)
        self._cancelled.discard(req_id)
        self.tracer.record("handoff_emit", t0, dt, request_id=req_id,
                           p=p, bytes=len(frame))
        # "t": emit stamp (this clock) — the broker subtracts it
        # (through the measured pipe clock offset) from its receipt
        # time, splitting handoff WIRE latency from serialize wall.
        self._write({"op": HostOp.HANDOFF, "id": req_id, "p": p,
                     "prompt_len": len(prompt_ids),
                     "nbytes": len(frame), "frame": b64,
                     "blocks": n_blocks, "shipped": n_blocks - len(skip),
                     "t": round(time.monotonic(), 4)})

    def _handle_adopt(self, msg: dict) -> None:
        """Decode-role command: submit the migrated request with an
        adoption thunk the SCHEDULER runs at admission pick. EVERYTHING
        frame-heavy — base64 decode, crc, structural validation, bucket
        padding, the host→device transfer, the store insert — lives in
        the thunk, on the engine thread: the prefix store's mutation
        contract is engine-thread-only, and a burst of multi-hundred-MB
        frames processed on THIS serial command loop would starve stats
        replies past the supervisor's wedge deadline and delay every
        queued cancel/submit behind them. The request is submitted with
        an EMPTY prompt; the thunk fills prompt_ids from the frame's
        tokens before the scheduler's lookup. A frame that fails ANY
        check (truncated, corrupt, wrong version, wrong geometry) fails
        this one request with an error event through the scheduler's
        admission error path — never adopts questionable KV, never
        kills the loop."""
        t_recv = time.monotonic()
        req_id = str(msg.get("id", ""))
        frame_b64 = msg.get("frame")
        if not isinstance(frame_b64, str) or not frame_b64:
            # adopt_stats is written from this pipe-reader thread AND
            # from the adopt thunk on the engine thread; every mutation
            # holds _wlock (symlint C202).
            with self._wlock:
                self.adopt_stats["errors"] += 1
            self._m_adopt_frames.inc(outcome="error")
            self._write({"op": HostOp.EVENT, "id": req_id, "text": "",
                         "done": True, "finish_reason": "error",
                         "error": "handoff adoption failed: adopt op "
                                  "carries no frame"}, events=1)
            return

        def adopt(req, frame_b64=frame_b64, req_id=req_id) -> None:
            from symmetry_tpu.engine.disagg import decode_kv_handoff

            t0 = time.monotonic()
            try:
                import base64

                raw = base64.b64decode(frame_b64, validate=True)
                handoff = decode_kv_handoff(raw)
                if handoff.request_id != req_id:
                    raise ValueError(
                        f"frame carries id {handoff.request_id!r}, "
                        f"command says {req_id!r}")
                req.prompt_ids = list(handoff.tokens)
                ok = (self._engine.adopt_prefix(handoff)
                      if handoff.p else False)
            except Exception as exc:  # noqa: BLE001 — fail one request
                with self._wlock:
                    self.adopt_stats["errors"] += 1
                self._m_adopt_frames.inc(outcome="error")
                raise RuntimeError(
                    f"handoff adoption failed: {exc}") from exc
            dt = time.monotonic() - t0
            with self._wlock:
                self.adopt_stats["frames"] += 1
                self.adopt_stats["bytes"] += len(raw)
                self.adopt_stats["deserialize_s"] += dt
                if handoff.p:
                    if ok:
                        self.adopt_stats["adopted"] += 1
                    else:
                        # Store rejected (budget): full prefill fallback
                        # — slower but still token-identical for greedy.
                        self.adopt_stats["rejected"] += 1
            self._m_adopt_deserialize.observe(dt)
            if handoff.p:
                self._m_adopt_frames.inc(
                    outcome="adopted" if ok else "rejected")
            else:
                # p == 0 routing-only frames count too — the registry
                # total must agree with adopt_stats["frames"], the same
                # quantity on the stats() surface.
                self._m_adopt_frames.inc(outcome="routing_only")

        s = msg.get("sampling") or {}
        resume = msg.get("resume") if isinstance(msg.get("resume"), dict) \
            else None
        max_new = int(msg.get("max_new", 512))
        resume_offset = 0
        if resume is not None:
            try:
                # A resumed migration: the emitted tokens already ride
                # the frame (the prefill tier appended them to the
                # prompt), so the resolved ids are discarded — this
                # tier only restores the RNG lane position and the
                # remaining token budget (resolve_resume: the shared
                # implementation; a negative claim fails this one
                # request, never the loop).
                from symmetry_tpu.engine.tokenizer import resolve_resume

                _, max_new, resume_offset = resolve_resume(
                    self._engine.tokenizer, resume, [], max_new)
            except Exception as exc:  # noqa: BLE001 — bad resume → event
                with self._wlock:
                    self.adopt_stats["errors"] += 1
                self._m_adopt_frames.inc(outcome="error")
                self._write({"op": HostOp.EVENT, "id": req_id,
                             "text": "", "done": True,
                             "finish_reason": "error",
                             "error": f"handoff adoption failed: {exc}"},
                            events=1)
                return
            if resume is not None and max_new == 0:
                # Budget already spent by the interrupted stream — only
                # the finish frame was lost; complete without admitting.
                self._write({"op": HostOp.EVENT, "id": req_id,
                             "text": "", "done": True,
                             "finish_reason": "length",
                             "tokens": resume_offset, "tokens_new": 0,
                             "resume_from": resume_offset}, events=1)
                return
        sampling = SamplingParams(
            temperature=float(s.get("temperature", 0.0)),
            top_p=float(s.get("top_p", 1.0)),
            top_k=int(s.get("top_k", 0)),
            seed=s.get("seed"),
            rng_skip=resume_offset,
        )
        self._reported[req_id] = 0

        def emit(ev, req_id=req_id) -> None:
            self._write({"op": HostOp.EVENT, **self._event_dict(req_id, ev)},
                        events=1)

        spec = msg.get("speculative")
        deadline = msg.get("deadline_s")
        trace_id = str(msg.get("trace") or "")
        self._scheduler.submit(GenRequest(
            # Filled by the adopt thunk from the frame's tokens at
            # admission pick (the whole frame parse runs there).
            prompt_ids=[], sampling=sampling,
            max_new_tokens=max_new,
            emit=emit,
            cancelled=lambda: req_id in self._cancelled,
            id=req_id,
            speculative=spec if isinstance(spec, bool) else None,
            trace_id=trace_id,
            resume_offset=resume_offset,
            adopt=adopt,
            # Rebased by the broker for prefill-tier time already spent;
            # may arrive negative — the scheduler then sheds "expired".
            deadline_at=(t_recv + float(deadline)
                         if deadline is not None else None)))
        self.tracer.record("host_adopt", t_recv,
                           time.monotonic() - t_recv, request_id=req_id,
                           trace_id=trace_id, frame_b64_len=len(frame_b64))


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: python -m symmetry_tpu.engine.host <config.yaml>",
              file=sys.stderr)
        return 2
    host = EngineHost(ConfigManager(config_path=sys.argv[1]))
    return host.serve_forever()


if __name__ == "__main__":
    sys.exit(main())
