"""Continuous batching scheduler: requests in, per-request token streams out.

The reference's hot loop pumped one HTTP response per peer with backpressure
(reference: src/provider.ts:240-258). Here the equivalent loop is the decode
step over a slot batch: requests are inserted the moment a slot frees
(insert-on-arrival), every step advances all active slots one token, and
slots are evicted on EOS / token budget / client cancellation — BASELINE
config 3 (16 concurrent clients, continuous batching).

Threading model: one dedicated engine thread owns all JAX calls (the engine
is single-threaded by contract); asyncio callers talk to it through
queue.Queue (in) and asyncio-loop-safe callbacks (out). This preserves the
reference's "all concurrency in one event loop" simplicity (SURVEY §5.2)
while keeping device dispatch off the loop.

Slot-accounting invariants are checked every step when `debug_invariants`
is on (SURVEY §5.2: an invariant-checking debug mode for the batch
scheduler): a slot is in exactly one of {free, active}; an active slot's
request has a live stream; cache length never exceeds capacity.
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.ledger import RequestLedger
from symmetry_tpu.engine.tokenizer import StreamDecoder
from symmetry_tpu.utils.faults import FAULTS, InjectedFault
from symmetry_tpu.utils.logging import logger as log


@dataclass
class GenRequest:
    """One generation job as the scheduler sees it."""

    prompt_ids: list[int]
    sampling: SamplingParams
    max_new_tokens: int
    # Called from the engine thread via loop.call_soon_threadsafe.
    emit: Callable[["TokenEvent"], None]
    cancelled: Callable[[], bool] = lambda: False
    id: str = ""
    # Per-request speculative-decoding override: False opts this request
    # out of drafting (its slot rides plain decode lanes); None/True defer
    # to the engine's tpu.speculative knob. No effect when the knob is off.
    speculative: bool | None = None
    # Request trace context: the id the client minted, threaded through
    # provider → host pipe → here, so scheduler spans for this request
    # land on the same Perfetto timeline as everyone else's.
    trace_id: str = ""
    # Decode-tier handoff adoption (engine/disagg/): called ONCE with
    # this request on the engine thread when admission first picks it,
    # BEFORE the prefix lookup — the radix index's mutation contract is
    # engine-thread-only, and the adoption's heavy work (frame decode,
    # device transfer) belongs next to the other admission device work,
    # not on the host's serial wire thread. The thunk fills
    # `prompt_ids` from the frame's tokens (the request is submitted
    # with an empty prompt) and seeds the store. Raising fails this
    # request with an error event (never the loop).
    adopt: Callable[["GenRequest"], None] | None = None
    # Absolute CLOCK_MONOTONIC deadline (client deadline_s mapped through
    # provider → host receipt). A request whose deadline has already
    # passed when admission picks it is shed with finish_reason
    # "expired" instead of prefilled — under backlog, prefilling work
    # nobody is waiting for steals device time from requests that still
    # have a live consumer. None = no deadline.
    deadline_at: float | None = None
    # Stream resumption: how many completion tokens the client already
    # holds (prompt_ids then carries prompt + re-encoded emitted text,
    # and sampling.rng_skip repositions a seeded lane). Admission books
    # it under the sym_resume_* families; the first event echoes it as
    # `resumed_from` so the relay can offset-dedup any overlap.
    resume_offset: int = 0
    # Radix-cache tokens this admission reused (stamped by _place_group
    # when a prefix hit covers the prompt): the first event carries it,
    # and a resume admission with reused > 0 is the cheap seeded
    # re-prefill the resume path exists for (vs a full re-prefill).
    reused_tokens: int = 0
    # symledger cost account (engine/ledger.py), opened by submit()
    # while tpu.ledger is on; None otherwise — every booking site is
    # then one `is not None` branch (the disabled-mode contract).
    ledger: Any = None
    enqueued_at: float = field(default_factory=time.monotonic)
    # Stamped when the request enters a placement group (the admission
    # moment); re-stamped on re-pick after a budget deferral, so
    # picked_at - enqueued_at is the true scheduler queue wait. Feeds the
    # per-stage TTFT breakdown (TokenEvent.stages).
    picked_at: float | None = None


@dataclass(slots=True)
class TokenEvent:
    """One streamed increment: text delta and/or terminal marker."""

    text: str
    token_id: int | None
    done: bool = False
    # "stop" | "length" | "cancelled" | "error" | "expired"
    finish_reason: str | None = None
    error: str | None = None
    # serving metrics (SURVEY §5.1: TTFT and tok/s are first-class)
    ttft_s: float | None = None
    tokens_generated: int = 0
    # Cumulative tokens actually EMITTED as text (pushed to the stream
    # decoder) — excludes the EOS token and anything a finishing block
    # discarded past it, so deltas of this field sum to exactly what the
    # client streamed (the host's tokens_new and the bench's
    # tokens_streamed both ride it; tokens_generated keeps the
    # budget-accounting convention of counting the EOS).
    tokens_emitted: int = 0
    # Per-stage monotonic stamps, attached ONCE per request (its first
    # event): {"recv": host received, "picked": entered a placement
    # group, "first": first token sampled}. The host adds its pipe-write
    # stamp and the provider closes the chain — the end-to-end TTFT
    # attribution of round-4 task #3 (CLOCK_MONOTONIC is one clock
    # across processes on Linux, same contract the bench workers use).
    stages: dict | None = None
    # First-event-only resume/reuse stamps (None elsewhere): tokens this
    # admission pulled from the radix cache, and — for a resumed request
    # — the completion offset generation continued from (the relay's
    # offset-dedup input).
    tokens_reused: int | None = None
    resumed_from: int | None = None
    # Terminal-event-only symledger cost block (engine/ledger.py):
    # device_s{phase} / queue_s / emit_s / wasted_s{reason} / saved_s,
    # attributed at dispatch granularity. None mid-stream, and None on
    # terminal events while tpu.ledger is off.
    costs: dict | None = None


@dataclass
class _ActiveSlot:
    req: GenRequest
    decoder: StreamDecoder
    generated: int = 0
    emitted: int = 0   # tokens pushed to the decoder (streamed as text)
    prompt_len: int = 0
    first_token_at: float | None = None
    stages_sent: bool = False


class Scheduler:
    """Drives an InferenceEngine from a request queue on its own thread."""

    def __init__(self, engine: InferenceEngine, *,
                 debug_invariants: bool = False,
                 prefill_chunks_per_block: int = 4,
                 admit_groups_per_block: int = 4,
                 admit_seconds_per_block: float = 0.65,
                 pipeline_depth: int = 2,
                 emit_queue_blocks: int = 8,
                 emit_batch: Callable[
                     [list[tuple[GenRequest, TokenEvent]]], None]
                 | None = None,
                 handoff: Callable[[int, GenRequest, int], None]
                 | None = None,
                 ledger_enabled: bool = True) -> None:
        self.engine = engine
        # Disaggregated tier role (engine/disagg/): mirrors the engine's.
        # "prefill" replaces slot activation with the handoff sink — a
        # request that would have started decoding is instead serialized
        # and shipped (the sink extracts + writes the frame, called on
        # the engine thread), its slot freed immediately. "decode" books
        # adopted-prefix suffix dispatches under adopt_* instead of
        # admit_* (a decode-tier host must report ZERO admission-prefill
        # wall — the prefill tier owns that work now). "unified" is
        # byte-identical to the pre-disagg scheduler.
        self._role = getattr(engine, "role", "unified")
        self._handoff = handoff
        if self._role == "prefill" and handoff is None:
            raise ValueError("role: prefill scheduler requires a handoff "
                             "sink — prefilled requests have nowhere to go")
        self._inbox: queue.Queue[GenRequest | None] = queue.Queue()
        # Budget-deferred admissions wait HERE, not at the inbox tail:
        # re-queuing a deferred subgroup behind later arrivals inverted
        # FIFO order every block it stayed deferred, unboundedly inflating
        # that request's TTFT under sustained load. Drained before the
        # inbox on the next _admit_new pass, so arrival order holds.
        self._deferred: deque[GenRequest] = deque()
        self._slots: dict[int, _ActiveSlot] = {}
        self._free: list[int] = list(range(engine.max_slots))[::-1]
        # Block-granular emit: events buffer on the engine thread and are
        # delivered at block boundaries — as ONE emit_batch call when a
        # sink is installed (the host pipe writes one frame per block), or
        # per-event through req.emit otherwise (AsyncSession, tests).
        self._emit_batch = emit_batch
        self._pending_events: list[tuple[GenRequest, TokenEvent]] = []
        # Overlapped pipeline (ROADMAP item 2): keep up to `pipeline_depth`
        # decode blocks dispatched-but-unsynced between iterations, so the
        # host's per-block work (detokenize, event encode, pipe emit,
        # bookkeeping) overlaps device execution instead of serializing
        # with it. Depth 1 reproduces the pre-pipeline double-buffer loop
        # exactly (the A/B baseline).
        self._depth = max(1, int(pipeline_depth))
        # Emit/bookkeep offload (depth >= 2): everything that is not a
        # device dispatch — push_many detokenize, TokenEvent construction,
        # stage-stamp decoration, emit_batch/req.emit delivery — runs on a
        # dedicated worker thread fed per-block job batches through a
        # BOUNDED queue. The bound is the backpressure contract: a slow
        # pipe consumer makes the blocking put below stall the dispatch
        # thread rather than queue events without limit. All events flow
        # through the queue while offload is on (never a mix of inline and
        # queued delivery), so per-request wire order is exactly the
        # engine-thread production order. `_emit_offload` is written ONLY
        # by start() before the threads exist; everywhere else reads it.
        self._emit_queue: queue.Queue[list[tuple] | None] = queue.Queue(
            maxsize=max(1, int(emit_queue_blocks)))
        self._emit_thread: threading.Thread | None = None
        self._emit_offload = False
        self._block_jobs: list[tuple] = []
        # Worker-owned counters (merged into stats() reads): the worker
        # never touches self.metrics — key ownership stays single-thread.
        self._wmetrics = {"offloaded_s": 0.0, "emit_flushes": 0,
                          "emit_events": 0}
        self._live_depth = 0
        # Vectorized terminal scan over each [K, B] block needs the EOS
        # set as an array once, not a per-token set probe.
        self._eos_arr = np.array(sorted(engine.tokenizer.eos_ids),
                                 dtype=np.int64)
        # Long prompts prefill chunk-by-chunk between decode blocks
        # (engine.ChunkedPrefill); short bursts are capped per block. Both
        # bound how long active streams stall on admission work — the
        # round-2 verdict's inter-token-p99 complaint.
        self._prefill_jobs: list[tuple[Any, GenRequest]] = []
        self._chunks_per_block = prefill_chunks_per_block
        self._admit_groups = admit_groups_per_block
        # The binding admission bound while streams are active is TIME, not
        # count, shared by burst admissions and chunked-prefill advances:
        # stop admitting once the block's admission work exceeds this many
        # seconds (one dispatch may overshoot — admissions are atomic).
        # Measured on-chip (round 4): prefill dispatches overlap the
        # in-flight decode block (async dispatch), so engine-side block
        # intervals stay <= ~1.6x block time even at 2 wide admissions
        # per block — while halving the budget to one dispatch per block
        # only stretched the ramp (TTFT p50 5.0 -> 7.0 s) without moving
        # the client-observed gap. 0.65 allows ~2 batch-16 prefills per
        # block; the count caps remain as secondary bounds.
        self._admit_budget_s = admit_seconds_per_block
        self._spent_this_block = 0.0
        self._debug = debug_invariants
        self._thread: threading.Thread | None = None
        self._stopping = threading.Event()
        # Speculative decoding (engine/spec/): when the engine was built
        # with tpu.speculative, the scheduler owns the host-side n-gram
        # drafter and interleaves verify dispatches with plain decode
        # blocks. Engine spec None => self._drafter None => every code
        # path below is byte-identical to the non-speculative scheduler.
        spec = getattr(engine, "spec", None)
        if spec is not None:
            from symmetry_tpu.engine.spec import NGramDrafter

            self._drafter: NGramDrafter | None = NGramDrafter(spec)
        else:
            self._drafter = None
        # KV writes one dispatch can land for a slot: a verify dispatch
        # touches 1 + k_draft positions where a plain block touches
        # decode_block — the capacity guards must fence the larger.
        self._max_block_writes = max(
            engine.decode_block,
            (1 + spec.k_draft) if spec is not None else 0)
        self.metrics = {"requests": 0, "tokens": 0, "evictions": 0,
                        "steps": 0, "peak_occupancy": 0,
                        # Requests shed at admission because their
                        # end-to-end deadline had already expired (the
                        # overload-round accounting: prefill work saved).
                        "deadline_shed": 0,
                        # Stream resumption (all 0 without resumes):
                        # resume submissions, completion tokens they
                        # skipped regenerating, and the radix-cache
                        # tokens their admissions reused instead of
                        # re-prefilling (reused > 0 is the cheap-resume
                        # contract the kill-under-load round asserts).
                        "resumes": 0, "resumed_tokens": 0,
                        "resume_reused_tokens": 0,
                        # Per-phase wall accounting (round-3 verdict: a
                        # benchmark capture must carry its own explanation):
                        # admission prefill dispatches, chunked-prefill
                        # advances, decode-block syncs — each phase's count
                        # and cumulative seconds, read via stats().
                        "admit_dispatches": 0, "admit_s": 0.0,
                        "chunk_dispatches": 0, "chunk_s": 0.0,
                        "block_syncs": 0, "sync_s": 0.0,
                        # Emit-path accounting: flushes = batch deliveries
                        # (one per block boundary with events pending),
                        # events = TokenEvents carried. events/flushes is
                        # the coalescing ratio the batched host frame
                        # exists to raise.
                        "emit_flushes": 0, "emit_events": 0,
                        # Disaggregation (all 0 outside the tier roles):
                        # prefill tier — requests handed off + serialize/
                        # extract wall; decode tier — adopted-prefix
                        # suffix dispatches, booked HERE so admit_* stays
                        # zero on a host that does no admission prefill.
                        "handoffs": 0, "handoff_s": 0.0,
                        "adopt_dispatches": 0, "adopt_s": 0.0,
                        # Speculative decoding (all 0 with the knob off):
                        # verify dispatches, tokens the drafter proposed,
                        # tokens the target accepted, and tokens rolled
                        # back (drafted - accepted); spec_verify_s is the
                        # wall spent in verify dispatch+sync.
                        "spec_verify_blocks": 0, "spec_drafted": 0,
                        "spec_accepted": 0, "spec_rolled_back": 0,
                        "spec_tokens": 0, "spec_verify_s": 0.0,
                        # Dispatch-thread wall: non-idle loop-iteration
                        # seconds on the engine thread. Its counterpart,
                        # offloaded_s (emit-worker wall), lives in
                        # _wmetrics — the split is the CPU-verifiable
                        # proxy for dispatch_gap_share -> ~0.
                        "dispatch_thread_s": 0.0}
        from symmetry_tpu.utils.metrics import METRICS, MetricName
        from symmetry_tpu.utils.trace import Histogram, Tracer

        # Always-on time series (utils/metrics.py): the same counters the
        # stats() snapshot reports, but as registry families a Prometheus
        # scrape / symtop poll reads without a stats round-trip. Emitted
        # at block/dispatch granularity only — never per token — and
        # disabled-mode cost is one branch (metrics.enabled: false).
        self._m_requests = METRICS.counter(
            MetricName.SCHED_REQUESTS, "requests submitted to the scheduler")
        self._m_tokens = METRICS.counter(
            MetricName.SCHED_TOKENS, "tokens emitted by the engine")
        self._m_queue_depth = METRICS.gauge(
            MetricName.SCHED_QUEUE_DEPTH,
            "inbox + budget-deferred admission backlog")
        self._m_occupancy = METRICS.gauge(
            MetricName.SCHED_OCCUPANCY, "active decode slots")
        self._m_evictions = METRICS.counter(
            MetricName.SCHED_EVICTIONS, "slots released (request finished)")
        self._m_deadline_sheds = METRICS.counter(
            MetricName.SCHED_DEADLINE_SHEDS,
            "requests shed at admission on an expired deadline")
        self._m_handoffs = METRICS.counter(
            MetricName.SCHED_HANDOFFS,
            "prefill-tier requests handed off to the decode tier")
        self._m_dispatch = METRICS.histogram(
            MetricName.SCHED_DISPATCH,
            "device dispatch wall per kind", labels=("kind",))
        self._m_ttft = METRICS.histogram(
            MetricName.SCHED_TTFT,
            "engine-side TTFT (enqueue to first sampled token)")
        self._m_resumes = METRICS.counter(
            MetricName.SCHED_RESUMES,
            "resume submissions admitted (mid-stream recovery)")
        self._m_resumed_tokens = METRICS.counter(
            MetricName.SCHED_RESUMED_TOKENS,
            "completion tokens resumes skipped regenerating")
        self._m_resume_reused = METRICS.counter(
            MetricName.SCHED_RESUME_REUSED,
            "radix-cache tokens resume admissions reused")
        # The overlap split: time the dispatch thread actually spends per
        # non-idle iteration vs time the emit worker spends delivering the
        # offloaded per-block work. At depth >= 2 the first should approach
        # the bare dispatch cost; the second absorbs everything else.
        self._m_dispatch_thread = METRICS.histogram(
            MetricName.SCHED_DISPATCH_THREAD,
            "dispatch-thread wall per non-idle loop iteration")
        self._m_offloaded = METRICS.histogram(
            MetricName.SCHED_OFFLOADED,
            "emit-worker wall per delivered job batch")
        self._m_pipeline_depth = METRICS.gauge(
            MetricName.SCHED_PIPELINE_DEPTH,
            "decode blocks in flight between loop iterations")

        # Request-scoped tracing (dispatch granularity — never per token):
        # every device dispatch (prefill/chunk/decode block/verify) and
        # every request's queue → prefill → generate phases land as spans
        # in this bounded ring, with queue-depth/occupancy counter tracks
        # stamped at block boundaries. Read via trace_export() through the
        # host-pipe `trace` op — a ring snapshot off the hot loop, never a
        # blocking call inside it. ~10 records per block: noise next to
        # the device sync it sits beside.
        self.tracer = Tracer(capacity=8192)
        # Engine-side latency distributions: TTFT as the scheduler saw it
        # (enqueue → first sampled token), admission dispatch wall, and the
        # interval between consecutive decode-block syncs while streams are
        # active (the engine-side bound on any client's inter-chunk gap —
        # if the client measures seconds and this says milliseconds, the
        # stall is in the relay/wire, not the engine).
        self._ttft_hist = Histogram()
        self._admit_hist = Histogram()
        self._adopt_hist = Histogram()
        # Block-sync intervals are PER KIND, and an interval is observed
        # only when the previous sync was the SAME kind: a decode_block ->
        # decode_block interval estimates block cadence, a verify ->
        # decode_block interval spans a one-forward dispatch and would
        # poison the percentiles (the old single histogram forced the
        # decode-floor metrics to be omitted whenever drafting was on).
        self._interval_hists = {"decode_block": Histogram(),
                                "verify": Histogram()}
        self._dispatch_thread_hist = Histogram()
        # Per-slot tokens emitted by each verify dispatch (1 = nothing
        # accepted, 1 + k_draft = the whole proposal) — the distribution
        # that says whether speculation is paying for its dispatches.
        self._spec_emit_hist = Histogram()
        self._last_sync_done: float | None = None
        self._last_sync_kind: str | None = None
        # symledger (engine/ledger.py, tpu.ledger): per-request device-
        # time attribution. Source flag: symprof sampling armed makes
        # the dispatch walls probe-synced ("probed"); otherwise they are
        # dispatch-thread block time ("blocked"). Disabled cost is one
        # guarded branch per dispatch — track() returns None, and every
        # booking site checks `req.ledger is not None` / ledger.enabled.
        dp0 = getattr(engine, "devprof", None)
        self.ledger = RequestLedger(
            enabled=ledger_enabled,
            measured=dp0 is not None and dp0.enabled)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._depth > 1:
            # Offload engages only while the worker is actually running:
            # white-box tests (and the engine-death path after join) drive
            # scheduler internals without start() and must keep the
            # inline emit path.
            self._emit_thread = threading.Thread(
                target=self._emit_worker_run, name="emit-worker",
                daemon=True)
            self._emit_offload = True
            self._emit_thread.start()
        self._thread = threading.Thread(target=self._run, name="engine-loop",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful drain: no new inserts, finish active slots, then join.

        (The reference never drained in-flight requests on shutdown —
        SURVEY §3.4 calls that out; we do.)
        """
        self._stopping.set()
        self._inbox.put(None)  # wake the loop
        if self._thread is not None:
            self._thread.join(timeout)

    def submit(self, req: GenRequest) -> None:
        if self._stopping.is_set():
            raise RuntimeError("scheduler is stopping")
        self.metrics["requests"] += 1
        self._m_requests.inc()
        if req.resume_offset > 0:
            # Booked at submit (same thread-ownership as "requests"):
            # the tokens this resume did NOT regenerate are the saved
            # work the kill-under-load round headlines.
            self.metrics["resumes"] += 1
            self.metrics["resumed_tokens"] += req.resume_offset
            self._m_resumes.inc()
            self._m_resumed_tokens.inc(req.resume_offset)
        # Cost account opens at submission (None while tpu.ledger is
        # off). Stored on the request: ownership rides the request
        # through every exit path, and the terminal-event seams
        # (_finish / _emit_cb) close it wherever the request dies.
        req.ledger = self.ledger.track(req.id)
        self._inbox.put(req)

    @property
    def occupancy(self) -> int:
        return len(self._slots)

    def stats(self) -> dict[str, Any]:
        """Counters + engine-side latency percentiles (host stats op)."""
        out: dict[str, Any] = dict(self.metrics)
        out["role"] = self._role
        out["occupancy"] = len(self._slots)
        if self._adopt_hist.count:
            out["adopt_dispatch_s"] = self._adopt_hist.to_dict()
        # Gauges for the two admission backlogs that were invisible in
        # host→provider stats: the budget-deferred deque and the
        # chunked-prefill jobs still building their prefixes.
        out["deferred_depth"] = len(self._deferred)
        out["prefill_jobs_active"] = len(self._prefill_jobs)
        # Total admission backlog (inbox + deferred) — the same number
        # the sym_sched_queue_depth gauge tracks, surfaced in the stats
        # reply so the pool router's heartbeat can feed placement with
        # REAL backlog instead of only its own in-flight counts.
        out["queue_depth"] = self._inbox.qsize() + len(self._deferred)
        out["engine_ttft_s"] = self._ttft_hist.to_dict()
        out["admit_dispatch_s"] = self._admit_hist.to_dict()
        out["block_interval_s"] = self._interval_hists["decode_block"].to_dict()
        if self._interval_hists["verify"].count:
            out["verify_interval_s"] = self._interval_hists["verify"].to_dict()
        # The overlap split (the tentpole's CPU-verifiable target): wall
        # the dispatch thread spends per non-idle iteration vs wall the
        # emit worker spends on the offloaded per-block work, plus the
        # configured and LIVE pipeline depth and the emit-queue backlog.
        out["pipeline_depth"] = self._depth
        out["pipeline_live_depth"] = self._live_depth
        # 6 decimals, not 4: a tiny-model CPU run's whole offloaded wall
        # is tens of microseconds, and the smoke asserts it is nonzero.
        out["offloaded_s"] = round(self._wmetrics["offloaded_s"], 6)
        out["emit_flushes"] = (self.metrics["emit_flushes"]
                               + self._wmetrics["emit_flushes"])
        out["emit_events"] = (self.metrics["emit_events"]
                              + self._wmetrics["emit_events"])
        out["emit_queue_depth"] = self._emit_queue.qsize()
        if self._dispatch_thread_hist.count:
            out["dispatch_thread_block_s"] = (
                self._dispatch_thread_hist.to_dict())
        # Decode-floor metrics (the convert-wall number, in EVERY driver
        # bench capture instead of only the engine-only bench): per-step
        # decode wall from the block-interval p50 (intervals spanning
        # admissions land in the upper percentiles, so p50 is the
        # steady-state estimate), and the weight bytes that step must
        # stream — their ratio is the effective weight-stream HBM GB/s.
        # Intervals are per-kind and same-kind-only, so speculative
        # verify dispatches no longer poison the decode_block histogram —
        # the metrics hold with drafting on (pre-pipeline they had to be
        # omitted in speculative mode).
        iv_p50 = self._interval_hists["decode_block"].percentile(50)
        wsb = getattr(self.engine, "weight_stream_bytes", None)
        if iv_p50:
            step_s = iv_p50 / self.engine.decode_block
            out["decode_step_ms"] = round(1e3 * step_s, 3)
            if wsb is not None:
                nbytes = int(wsb())
                out["weight_bytes_per_step"] = nbytes
                out["weight_stream_gbs"] = round(nbytes / step_s / 1e9, 1)
                # Per-device shard stream (sharded packed layout): the
                # per-chip HBM roofline number the TP A/B gate reads.
                wsbd = getattr(self.engine,
                               "weight_stream_bytes_per_device", None)
                if wsbd is not None:
                    out["weight_stream_gbs_per_device"] = round(
                        int(wsbd()) / step_s / 1e9, 1)
        # symprof device-time attribution (utils/devprof.py,
        # tpu.profile_sample): per-dispatch-kind DEVICE-duration
        # percentiles + the dispatch-gap distribution/share, riding the
        # same host stats op → provider engine block → bench JSON.
        # Annotated with the pipeline depth: a probe's sync serializes
        # behind every in-flight block, so at depth >= 2 the next gap
        # sample measures the post-drain refill — gap_share is then an
        # UPPER bound on true device idle, and consumers must read it
        # against this depth (the documented accounting rule).
        dp = getattr(self.engine, "devprof", None)
        if dp is not None and dp.enabled:
            out["devprof"] = dict(dp.stats())
            out["devprof"]["pipeline_depth"] = self._depth
        # Shared-prefix KV cache counters (hit/miss/evict/bytes) ride the
        # same host stats op so they surface provider- and bench-side.
        pc_stats = getattr(self.engine, "prefix_cache_stats", None)
        if pc_stats is not None:
            pc = pc_stats()
            if pc is not None:
                out["prefix_cache"] = pc
        # Speculative-decoding block (host stats → provider stats → bench):
        # drafted/accepted/rolled-back counters, the acceptance rate, and
        # the per-slot tokens-per-verify-dispatch distribution.
        if self._drafter is not None:
            drafted = self.metrics["spec_drafted"]
            out["speculative"] = {
                "k_draft": self._drafter.config.k_draft,
                "verify_blocks": self.metrics["spec_verify_blocks"],
                "drafted": drafted,
                "accepted": self.metrics["spec_accepted"],
                "rolled_back": self.metrics["spec_rolled_back"],
                "acceptance_rate": (
                    round(self.metrics["spec_accepted"] / drafted, 4)
                    if drafted else None),
                "spec_tokens": self.metrics["spec_tokens"],
                "verify_s": round(self.metrics["spec_verify_s"], 3),
                "tokens_per_dispatch": self._spec_emit_hist.to_dict(),
            }
        # symledger rider (engine/ledger.py): bounded finished-request
        # ring + cumulative attribution aggregates, riding the same
        # host STATS op → provider engine block → bench JSON as every
        # other block above. Absent entirely while tpu.ledger is off.
        if self.ledger.enabled:
            out["ledger"] = self.ledger.stats()
        return out

    def trace_export(self) -> dict[str, Any]:
        """Span/counter rings as one export_perfetto component (the
        host-pipe `trace` op's scheduler entry)."""
        return self.tracer.component("scheduler")

    # ------------------------------------------------------------- the loop

    def _run(self) -> None:
        """Thread target: contain crashes so no stream ever hangs open."""
        try:
            self._loop_forever()
        except BaseException as exc:  # noqa: BLE001 — fatal engine failure
            log.error(f"engine loop died: {exc!r}; failing open streams")
            for slot, active in list(self._slots.items()):
                ev = TokenEvent(
                    text="", token_id=None, done=True, finish_reason="error",
                    error=f"engine failure: {exc}")
                if active.req.ledger is not None:
                    # Engine death is an exit path too: the entry closes
                    # and the error event still carries its costs.
                    ev.costs = active.req.ledger.finish("error")
                self._emit(active, ev)
                del self._slots[slot]
            while self._deferred:
                self._emit_cb(self._deferred.popleft(), TokenEvent(
                    text="", token_id=None, done=True,
                    finish_reason="error", error=f"engine failure: {exc}"))
            for _job, req in self._prefill_jobs:
                self._emit_cb(req, TokenEvent(
                    text="", token_id=None, done=True,
                    finish_reason="error", error=f"engine failure: {exc}"))
            self._prefill_jobs.clear()
            while True:
                try:
                    item = self._inbox.get_nowait()
                except queue.Empty:
                    break
                if item is not None:
                    self._emit_cb(item, TokenEvent(
                        text="", token_id=None, done=True,
                        finish_reason="error", error=f"engine failure: {exc}"))
            self._flush_events()
            raise
        finally:
            # Runs AFTER the except block above, so the error events it
            # queued are delivered before the worker sees the sentinel.
            self._stop_emit_worker()

    def _stop_emit_worker(self) -> None:
        """Drain residual jobs, send the shutdown sentinel, and join the
        emit worker. Engine-thread only (the loop's exit path)."""
        if self._emit_thread is None:
            return
        if self._block_jobs:
            jobs, self._block_jobs = self._block_jobs, []
            self._emit_queue.put(jobs)
        self._emit_queue.put(None)
        self._emit_thread.join(timeout=10.0)

    # ------------------------------------------------------ emit offload

    def _emit_worker_run(self) -> None:
        """Worker thread target: deliver job batches until the sentinel.

        Every batch is exception-contained — a worker death with the
        queue full would deadlock the dispatch thread's blocking put, so
        nothing may escape this loop short of the sentinel."""
        while True:
            jobs = self._emit_queue.get()
            if jobs is None:
                return
            try:
                self._deliver_jobs(jobs)
            except Exception as exc:  # noqa: BLE001 — worker must not die
                log.error(f"emit worker batch failed: {exc}")

    def _deliver_jobs(self, jobs: list[tuple]) -> None:
        """Run one block's jobs (detokenize + event build) and deliver
        the resulting events exactly like the inline _flush_events path:
        one emit_batch call with a sink installed, else per-event
        req.emit. Worker thread; books into _wmetrics only."""
        t0 = time.monotonic()
        batch: list[tuple[GenRequest, TokenEvent]] = []
        for job in jobs:
            try:
                pair = self._run_job(job)
            except Exception as exc:  # noqa: BLE001 — fail one, not the batch
                log.error(f"emit job failed: {exc}")
                continue
            if pair is not None:
                batch.append(pair)
        if not batch:
            return
        self._wmetrics["emit_flushes"] += 1
        self._wmetrics["emit_events"] += len(batch)
        if self._emit_batch is not None:
            try:
                self._emit_batch(batch)
            except Exception as exc:  # noqa: BLE001 — must never kill the worker
                log.error(f"emit batch sink failed: {exc}")
            self.tracer.record("emit_flush", t0, time.monotonic() - t0,
                               events=len(batch))
        else:
            for req, ev in batch:
                try:
                    req.emit(ev)
                except Exception as exc:  # noqa: BLE001
                    log.error(
                        f"emit callback failed for request {req.id}: {exc}")
        dt = time.monotonic() - t0
        self._wmetrics["offloaded_s"] += dt
        self._m_offloaded.observe(dt)
        if self.ledger.enabled and dt > 0.0:
            # Best-effort emit attribution: this flush's wall splits
            # evenly over its events. A request whose finish rode this
            # very batch already closed its entry (book_emit no-ops) —
            # emit_s covers the pre-terminal flushes.
            per = dt / len(batch)
            for req, _ev in batch:
                if req.ledger is not None:
                    req.ledger.book_emit(per)

    def _submit_job(self, job: tuple) -> None:
        """Route one emit/bookkeep job: buffered for the worker while
        offload is on, else run inline right here (the pre-pipeline
        behavior, byte-identical — depth 1 and un-started schedulers)."""
        if self._emit_offload:
            self._block_jobs.append(job)
            return
        pair = self._run_job(job)
        if pair is not None:
            self._pending_events.append(pair)

    def _run_job(self, job: tuple
                 ) -> tuple[GenRequest, TokenEvent] | None:
        """Materialize one job into a deliverable (req, event) pair.

        Jobs carry tokens_generated/emitted BY VALUE: the engine thread
        keeps mutating the _ActiveSlot on later blocks while the worker
        processes earlier ones. The slot's StreamDecoder and stages_sent
        are owned by whichever side runs the jobs (exactly one — offload
        never mixes), in per-request FIFO order."""
        kind = job[0]
        if kind == "run":
            _k, active, run, last_tok, gen, emitted = job
            text = active.decoder.push_many(
                run.tolist() if hasattr(run, "tolist") else list(run))
            if not text:
                return None
            return self._decorate(active, TokenEvent(
                text=text, token_id=last_tok,
                tokens_generated=gen, tokens_emitted=emitted))
        if kind == "finish":
            _k, active, run, tok, reason, ttft, gen, emitted, costs = job
            toks = run.tolist() if hasattr(run, "tolist") else list(run)
            text = active.decoder.push_many(toks) if toks else ""
            tail = text + active.decoder.flush()
            return self._decorate(active, TokenEvent(
                text=tail, token_id=tok, done=True, finish_reason=reason,
                ttft_s=ttft, tokens_generated=gen, tokens_emitted=emitted,
                costs=costs))
        if kind == "first":
            _k, active, first, ttft = job
            text = active.decoder.push(first)
            if not text:
                return None
            return self._decorate(active, TokenEvent(
                text=text, token_id=first, tokens_generated=1,
                tokens_emitted=1, ttft_s=ttft))
        if kind == "emit":
            _k, active, ev = job
            return self._decorate(active, ev)
        # kind == "raw": pre-built event with no slot to decorate
        # (admission errors, queued cancels, deadline sheds).
        return job[1], job[2]

    def _loop_forever(self) -> None:
        # Pipelined decode (SURVEY §7 hard-part 3, ROADMAP item 2): up to
        # `pipeline_depth` blocks stay in flight on the device between
        # iterations while the host processes the oldest one. Each pending
        # entry is (kind, device tokens, slot snapshot at dispatch,
        # dispatch stamp, extra) — the snapshot attributes each lane's
        # tokens to the request that occupied it AT DISPATCH, so a lane
        # freed-and-reused between dispatch and sync never leaks the old
        # request's block into the new one (the stale-snapshot check in
        # _process_block), and a slot freed at block N is never
        # double-sampled by the already-in-flight block N+1: its lane
        # tokens there are simply discarded. Depth 1 degenerates to the
        # pre-pipeline double buffer: one dispatch ahead, processed the
        # next iteration.
        pending: deque[tuple] = deque()
        while True:
            t_iter = time.perf_counter()
            self._spent_this_block = 0.0
            # Dispatch the next block BEFORE this iteration's admission
            # work: decode blocks sit at the FRONT of the device queue and
            # admission prefills enqueue behind them, so a burst of
            # arrivals never delays the block active streams are waiting
            # on — the prefill lane is fully asynchronous to decode, and
            # prefix-cache seed gathers/scatters (cached-path admission,
            # decode-tier adoption) overlap every in-flight block.
            # (Measured motivation: steady wire throughput stuck at ~70%
            # of engine-only because prefill dispatches issued ahead of
            # the block stretched every block interval under continuous
            # admission — BASELINE.md rounds 3-4.) A slot admitted this
            # iteration joins the NEXT dispatch — its first token was
            # already sampled by its prefill dispatch, so TTFT is
            # untouched; only its second token waits the extra block(s).
            #
            # Speculative mode drains the pipeline before proposing: the
            # drafter extends continuations of the freshest emitted
            # context. The verify dispatch itself then joins the pipeline
            # like any block (the satellite fix for the old same-iteration
            # early sync); at depth 1 it is still synced in-iteration —
            # the pre-pipeline serial behavior, for the A/B.
            did_dispatch = False
            did_verify = False
            # Depth >= 2 syncs the OLDEST in-flight block FIRST — the
            # loop body the tentpole asks for: sync oldest -> sample
            # next -> dispatch. The pipeline still holds depth-1 newer
            # blocks through the sync, so the device never idles, and
            # every host decision below (drafter peek, verify drain,
            # admission) sees a context only ONE block stale instead of
            # `depth` — without this, the speculative peek at depth 2
            # lags the device by two blocks and misfires both ways
            # (drains that propose nothing, repetition spotted too late
            # to verify). Depth 1 cannot sync first without a device
            # bubble (nothing else would be in flight during the sync):
            # it keeps the pre-pipeline dispatch-then-process double
            # buffer at the bottom of the loop.
            if self._depth > 1 and len(pending) >= self._depth:
                self._process_pending(pending.popleft())
            if self._slots and self._drafter is not None:
                if pending and self._spec_peek():
                    while pending:
                        self._process_pending(pending.popleft())
                if self._slots and not pending:
                    vb = self._maybe_verify_block()
                    if vb is not None:
                        pending.append(vb)
                        did_dispatch = did_verify = True
            if self._slots and not did_dispatch and len(pending) <= self._depth:
                pending.append((
                    "decode_block", self.engine.decode_steps_dispatch(),
                    dict(self._slots), time.monotonic(), None))
                self.metrics["steps"] += self.engine.decode_block
                did_dispatch = True
            drained = self._admit_new()
            if not self._slots and not pending and not self._prefill_jobs:
                # Terminal/error events from the admission pass must reach
                # their consumers BEFORE blocking on an empty inbox.
                self._flush_events()
                # Idle boundary: the next block interval would span the
                # idle wait, which is not a serving stall.
                self._last_sync_done = None
                self._last_sync_kind = None
                self._live_depth = 0
                self._m_pipeline_depth.set(0)
                self.metrics["dispatch_thread_s"] += (
                    time.perf_counter() - t_iter)
                if self._stopping.is_set() and drained:
                    return
                # Idle: block until work arrives (no busy spin). Engines
                # with an idle_tick (multi-host rank 0) get a periodic
                # heartbeat so worker ranks' pending collective doesn't hit
                # the distributed runtime's timeout.
                tick = getattr(self.engine, "idle_tick", None)
                try:
                    item = self._inbox.get(
                        timeout=10.0 if tick is not None else None)
                except queue.Empty:
                    tick()
                    continue
                if item is None:
                    if self._stopping.is_set():
                        return
                    continue
                # Hand the popped item straight to admission (re-putting it
                # would reorder it BEHIND arrivals that raced in while we
                # were blocked — inverted FIFO for the earliest request).
                t_iter = time.perf_counter()
                self._admit_new(carry=item)
                self._flush_events()
                self.metrics["dispatch_thread_s"] += (
                    time.perf_counter() - t_iter)
                continue

            # (The next block was dispatched above, before admission;
            # syncing the oldest in-flight block below then overlaps the
            # newer blocks' device execution, while the admission
            # dispatches that just enqueued run after them, never ahead.)
            #
            # Chunked prefills ride between decode dispatches: a bounded
            # number of chunk dispatches per block keeps long-prompt
            # admission from stalling active streams for more than ~a
            # chunk's device time.
            self._advance_prefills()
            # Admission-time events (first tokens from placement, chunked-
            # prefill finishes, admission errors) leave NOW, before the
            # device sync below can hold them for up to a whole block —
            # first-token latency must not pay for block coalescing. One
            # extra pipe write per block at most: still O(1).
            self._flush_events()
            # Depth 1's process point (the pre-pipeline double buffer:
            # dispatch block N+1 above, sync block N here), and both
            # depths' drain path when nothing was dispatched (slots
            # emptied or stopping). A depth-1 verify syncs in the same
            # iteration — the pre-pipeline serial-verify behavior.
            # Depth >= 2 already synced its oldest block at the TOP of
            # the iteration, so len(pending) never exceeds depth here.
            if pending and (len(pending) > self._depth or not did_dispatch
                            or (did_verify and self._depth == 1)):
                self._process_pending(pending.popleft())
            # Block boundary: everything this iteration produced (block
            # deltas, finishes) leaves as one batch — the O(1)-writes-
            # per-block contract (one bounded-queue handoff per flush
            # point while offload is on).
            self._flush_events()
            self._live_depth = len(pending)
            self._m_pipeline_depth.set(len(pending))
            dt_iter = time.perf_counter() - t_iter
            self.metrics["dispatch_thread_s"] += dt_iter
            if did_dispatch:
                self._m_dispatch_thread.observe(dt_iter)
                self._dispatch_thread_hist.observe(dt_iter)
            if self._debug:
                self._check_invariants()

    def _process_pending(self, blk: tuple) -> None:
        """Sync + process one in-flight pipeline entry (FIFO order).

        Verify entries book their speculative accounting HERE, at sync
        time — the dispatch ran up to `pipeline_depth` iterations ago,
        overlapped with admission and emit work (spec_verify_s is
        therefore dispatch -> sync wall, not pure device time)."""
        kind, toks_dev, snapshot, t0m, extra = blk
        if kind == "verify":
            n_emit_dev, n_draft, proposed = extra
            n_emit = np.asarray(n_emit_dev)
            dt = time.monotonic() - t0m
            accepted = int(np.sum(np.minimum(n_emit - 1, n_draft)))
            self.tracer.record("verify_dispatch", t0m, dt,
                               drafted=proposed, accepted=accepted)
            self.metrics["spec_verify_blocks"] += 1
            self.metrics["spec_verify_s"] += dt
            self.metrics["spec_drafted"] += proposed
            self.metrics["spec_accepted"] += accepted
            self.metrics["spec_rolled_back"] += proposed - accepted
            for slot in snapshot:
                if n_draft[slot]:
                    self._spec_emit_hist.observe(int(n_emit[slot]))
                    self.metrics["spec_tokens"] += int(n_emit[slot])
            spec_reject = None
            if self.ledger.enabled:
                # Per-slot rejected-draft fraction: of the 1 + k_draft
                # positions this slot's verify lane computed, the ones
                # past the acceptance point were device work rolled
                # back — that share of the slot's block attribution is
                # booked wasted_s{spec_rejected} in _process_block.
                spec_reject = {}
                for slot in snapshot:
                    nd = int(n_draft[slot])
                    if nd:
                        rej = nd - (int(n_emit[slot]) - 1)
                        if rej > 0:
                            spec_reject[slot] = (rej / (1.0 + nd), rej)
            self._process_block(toks_dev, snapshot, n_valid=n_emit,
                                dispatched_at=t0m, kind="verify",
                                spec_reject=spec_reject)
        else:
            self._process_block(toks_dev, snapshot, dispatched_at=t0m)

    def _process_block(self, device_toks: Any,
                       snapshot: dict[int, _ActiveSlot],
                       n_valid: np.ndarray | None = None,
                       dispatched_at: float | None = None,
                       kind: str = "decode_block",
                       spec_reject: dict[int, tuple[float, int]]
                       | None = None) -> None:
        """Sync one decode block to host and stream its tokens out.

        Batched pass (the block-granular emit path): ONE vectorized EOS
        scan over the whole [K, B] block, then per live slot one
        finish-point computation, one push_many over its token run, and
        one buffered TokenEvent — per-token Python work is gone, and the
        block boundary flush coalesces every slot's event into a single
        host-pipe frame.

        `n_valid` [B] makes the block RAGGED: slot b produced only
        n_valid[b] tokens this dispatch (>= 1). Plain decode blocks pass
        None (every slot advanced all K steps); speculative verify
        dispatches pass their per-slot accepted counts, so variable
        accepted-tokens-per-slot rides the same EOS/budget scan, the same
        push_many detokenize, and the same block-granular event frames.

        Token accounting: metrics["tokens"] (and TokenEvent.
        tokens_emitted) count only tokens PUSHED to the detokenizer —
        the EOS token and anything the block produced past a finish are
        discarded from the counters too, so the engine-side number sums
        to exactly the bench's tokens_streamed. tokens_generated keeps
        counting the EOS (the budget convention)."""
        t0 = time.perf_counter()
        toks = np.asarray(device_toks)  # blocks on THIS block only
        t1 = time.perf_counter()
        self.metrics["block_syncs"] += 1
        self.metrics["sync_s"] += t1 - t0
        # Same-kind-only intervals: a decode_block -> decode_block gap is
        # block cadence; an interval whose predecessor was a verify spans
        # a one-forward dispatch and lands in the verify histogram's
        # cadence instead — neither poisons the other's percentiles.
        if self._last_sync_done is not None and self._last_sync_kind == kind:
            self._interval_hists[kind].observe(t1 - self._last_sync_done)
        self._last_sync_done = t1
        self._last_sync_kind = kind
        if dispatched_at is not None:
            self._m_dispatch.observe(time.monotonic() - dispatched_at,
                                     kind=kind)
        # Block-boundary gauges: same cadence as the tracer's counter
        # tracks — a handful of registry ops per block, never per token.
        self._m_occupancy.set(len(self._slots))
        self._m_queue_depth.set(self._inbox.qsize() + len(self._deferred))
        if self.tracer.enabled:
            # Block span covers dispatch → device done (the device-side
            # wall the double buffer hides host work behind); the gauge
            # tracks are stamped once per block — boundary-granular, so
            # the hot loop never pays more than a few ring appends.
            t1m = time.monotonic()
            if dispatched_at is not None and kind == "decode_block":
                # (Verify entries record their own verify_dispatch span
                # in _process_pending.)
                self.tracer.record("decode_block", dispatched_at,
                                   t1m - dispatched_at,
                                   slots=len(snapshot),
                                   steps=int(toks.shape[0]))
            self.tracer.counter("occupancy", len(self._slots), t=t1m)
            self.tracer.counter(
                "queue_depth",
                self._inbox.qsize() + len(self._deferred), t=t1m)
        K = toks.shape[0]
        eos_mask = (np.isin(toks, self._eos_arr) if self._eos_arr.size
                    else np.zeros(toks.shape, dtype=bool))
        # symledger block attribution: the sync wall splits EQUALLY over
        # the snapshot lanes still live at sync (occupancy split — every
        # live lane's tokens rode the same device pass). One guarded
        # branch per dispatch when the ledger is off. A block whose
        # every lane went stale still burned the wall: booked
        # unattributed so conservation closes.
        led_share = 0.0
        led_phase = "verify" if kind == "verify" else "decode"
        if self.ledger.enabled:
            wall = t1 - t0
            n_live = sum(1 for s, a in snapshot.items()
                         if self._slots.get(s) is a)
            if n_live:
                led_share = wall / n_live
            else:
                self.ledger.book_unattributed(wall)
        block_tokens = 0
        for slot, active in snapshot.items():
            if self._slots.get(slot) is not active:
                continue  # finished in an earlier block; lane is stale
            if active.req.cancelled():
                # Discard the whole block remainder past the cancel.
                if active.req.ledger is not None:
                    # The cancelled lane's share of this block computed
                    # tokens the client will never see.
                    v_disc = K if n_valid is None else int(n_valid[slot])
                    active.req.ledger.book_device(led_phase, led_share)
                    active.req.ledger.book_wasted(
                        "cancelled", led_share, v_disc)
                self._finish(slot, active, "cancelled", None, ())
                continue
            # The request consumes tokens until the first EOS, its token
            # budget, or the block end — whichever comes first. An EOS at
            # the budget-exhausting position still finishes as "stop"
            # (EOS is checked before the length bound, matching the
            # per-token order this pass replaced). The EOS token counts
            # toward tokens_generated but is never detokenized or counted
            # as emitted.
            v = K if n_valid is None else int(n_valid[slot])
            budget = active.req.max_new_tokens - active.generated
            r = max(1, min(v, budget))
            hits = np.flatnonzero(eos_mask[:r, slot])
            if hits.size:
                e = int(hits[0])
                n_push, consumed, finish = e, e + 1, "stop"
            elif budget <= v:
                n_push = consumed = r
                finish = "length"
            else:
                n_push = consumed = v
                finish = None
            last_tok = int(toks[consumed - 1, slot])
            active.generated += consumed
            active.emitted += n_push
            block_tokens += n_push
            if active.req.ledger is not None:
                led = active.req.ledger
                led.book_device(led_phase, led_share, tokens=n_push)
                if spec_reject is not None and slot in spec_reject:
                    frac, rej = spec_reject[slot]
                    led.book_wasted("spec_rejected",
                                    led_share * frac, rej)
            # TWO dispatches' writes must stay within capacity after a
            # continue decision — the next block's (whose tokens we may
            # consume) plus one of margin (cache holds prompt_len +
            # generated - 1 entries after this block; a write is K
            # positions for a plain block, 1 + k_draft for a speculative
            # verify). The coefficient is depth-INDEPENDENT: any block we
            # continue INTO writes at <= c + writes <= c + 2*writes-worth
            # of positions by induction, while deeper pipelines only add
            # in-flight blocks whose tokens are discarded after a finish
            # (their past-capacity scatters are dropped against a lane
            # that is already released). Keeping the formula fixed keeps
            # finish="length" decisions — and therefore token identity —
            # bit-identical across pipeline depths.
            if finish is None and (
                    active.prompt_len + active.generated
                    + 2 * self._max_block_writes
                    > self.engine.slot_capacity + 1):
                finish = "length"
            if finish is None:
                if self._drafter is not None:
                    # Consumed tokens extend the slot's n-gram index (its
                    # context must track the device's conditioning).
                    # Engine-thread work: the next propose() reads it.
                    self._drafter.extend(slot, toks[:consumed, slot].tolist())
                if n_push:
                    # Counts snapshotted by value: the engine thread keeps
                    # advancing `active` on later blocks while the worker
                    # detokenizes this one.
                    self._submit_job(("run", active, toks[:n_push, slot],
                                      last_tok, active.generated,
                                      active.emitted))
            else:
                self._finish(slot, active, finish, last_tok,
                             toks[:n_push, slot])
        self.metrics["tokens"] += block_tokens
        if block_tokens:
            self._m_tokens.inc(block_tokens)

    def _spec_peek(self) -> bool:
        """Would any active slot propose a draft from its CURRENT
        context? Used while a plain block is still in flight — the
        context is stale by that block, so this is a predictor, not the
        proposal itself: a few dict probes per slot, no device work. A
        miss here just means one more overlapped plain block."""
        return any(
            active.req.speculative is not False
            and self._drafter.propose(slot)
            for slot, active in self._slots.items())

    def _maybe_verify_block(self) -> tuple | None:
        """Collect every active slot's n-gram proposal; when at least one
        slot has a draft, issue ONE verify dispatch (fixed [B, 1+k]
        shape) and return it as a pipeline entry — it is synced and its
        ragged output processed through the block pipeline like any
        in-flight block, so the host work between dispatch and sync
        overlaps the verify's device execution (the old path synced
        immediately, eating the overlap). Returns None — letting the
        caller fall back to a plain decode block — when nothing was
        proposed."""
        engine = self.engine
        k = engine.spec.k_draft
        draft = np.zeros((engine.max_slots, k), np.int32)
        n_draft = np.zeros((engine.max_slots,), np.int32)
        proposed = 0
        for slot, active in self._slots.items():
            if active.req.speculative is False:
                continue  # per-request opt-out: plain decode lanes only
            prop = self._drafter.propose(slot)
            if prop:
                draft[slot, :len(prop)] = prop
                n_draft[slot] = len(prop)
                proposed += len(prop)
        if not proposed:
            return None
        snapshot = dict(self._slots)
        t0m = time.monotonic()
        dispatch = getattr(engine, "verify_step_dispatch", None)
        if dispatch is not None:
            toks, n_emit = dispatch(draft, n_draft)
        else:
            # Engine (or test fake) without the async surface: the
            # synchronous host arrays ride the pipeline unchanged
            # (np.asarray at sync time is idempotent).
            toks, n_emit = engine.verify_step(draft, n_draft)
        self.metrics["steps"] += 1  # one forward advanced every lane
        return ("verify", toks, snapshot, t0m, (n_emit, n_draft, proposed))

    def _admit_new(self, carry: GenRequest | None = None) -> bool:
        """Place queued requests into free slots. Returns True if inbox
        empty. Concurrent arrivals coalesce into ONE prefill dispatch when
        the engine supports it (prefill_and_insert_many) — per-dispatch
        round-trips would otherwise serialize into the tail TTFT. `carry`
        is an already-popped request admitted ahead of the queue.

        While streams are active, at most `admit_groups_per_block` prefill
        DEVICE DISPATCHES are spent per call (a group spanning buckets
        costs one per bucket chunk): an admission burst would otherwise
        freeze every active stream for the whole burst. With nothing
        active there is nobody to stall — drain freely."""
        many = getattr(self.engine, "prefill_and_insert_many", None)
        batches_for = getattr(self.engine, "prefill_batches_for", None)
        if many is None:
            batch_cap = 1
        elif batches_for is not None:
            # Widest batch ANY bucket allows (the smallest bucket's cap);
            # _place_group re-partitions by bucket before dispatching.
            batch_cap = max(batches_for(self.engine.prefill_buckets[0]))
        else:
            batch_cap = max(getattr(self.engine, "PREFILL_BATCHES", (1,)))
        groups_left = (self._admit_groups
                       if (self._slots or self._prefill_jobs) else None)
        # (An occupancy-scaled budget — admit more aggressively while most
        # slots are free — was tried in round 4 and measured INERT at the
        # 128-burst point: the ramp is arrival-limited through the host
        # pipe, not budget-limited; 9 near-full dispatches either way.)
        while self._free:
            if groups_left is not None and (
                    groups_left <= 0
                    or self._spent_this_block >= self._admit_budget_s):
                break
            group: list[tuple[int, GenRequest]] = []
            while self._free and len(group) < batch_cap:
                if carry is not None:
                    item, carry = carry, None
                elif self._deferred:
                    # Budget-deferred subgroups from earlier blocks go
                    # first: they were popped from the inbox BEFORE
                    # everything still in it, so draining them first is
                    # what preserves arrival order.
                    item = self._deferred.popleft()
                else:
                    try:
                        item = self._inbox.get_nowait()
                    except queue.Empty:
                        break
                if item is None:
                    continue
                if FAULTS.enabled:
                    # scheduler.admit seam: error → this request fails
                    # with an error event; drop_frame → it silently
                    # vanishes (lost work — exactly what the supervisor's
                    # watchdog exists to notice); crash/hang act on the
                    # engine thread itself.
                    try:
                        if FAULTS.point("scheduler.admit"):
                            continue
                    except InjectedFault as exc:
                        self._emit_cb(item, TokenEvent(
                            text="", token_id=None, done=True,
                            finish_reason="error", error=str(exc)))
                        continue
                if item.cancelled():
                    # Cancelled while queued still gets its terminal event —
                    # the consumer is awaiting it.
                    self._emit_cb(item, TokenEvent(
                        text="", token_id=None, done=True,
                        finish_reason="cancelled"))
                    continue
                if (item.deadline_at is not None
                        and time.monotonic() > item.deadline_at):
                    # Deadline shed: the client (or its caller) stopped
                    # waiting before we could even place the request —
                    # prefilling it would bill the device for an answer
                    # nobody reads. Covers inbox and deferred entries
                    # alike (both pop through here).
                    self.metrics["deadline_shed"] += 1
                    self._m_deadline_sheds.inc()
                    if item.ledger is not None:
                        # Zero device seconds by construction (the shed
                        # IS the work avoided) — booked so the waste
                        # class is visible, with the queue wait the
                        # request burned getting nothing.
                        item.ledger.book_queue(
                            time.monotonic() - item.enqueued_at)
                        item.ledger.book_wasted("deadline_shed", 0.0)
                    late = time.monotonic() - item.deadline_at
                    self._emit_cb(item, TokenEvent(
                        text="", token_id=None, done=True,
                        finish_reason="expired",
                        error=f"deadline expired {late:.2f}s before "
                              f"admission"))
                    continue
                group.append((self._free.pop(), item))
            if not group:
                return self._inbox.empty()
            done = self._place_group(group)
            if groups_left is not None:
                # Budgeted by DEVICE DISPATCH, not by group: a group that
                # spans buckets (or exceeds a bucket's batch cap) costs
                # several dispatches, and each one stalls active streams.
                groups_left -= max(done, 1)
            else:
                # Unbudgeted cold-burst drain (nothing was decoding): a
                # large burst spans many placement groups, so each
                # group's first tokens leave NOW rather than after the
                # whole drain — the earliest request's delivered TTFT
                # must not pay for the rest of the burst's admission.
                # Still one write per placement group, not per event.
                self._flush_events()
        if carry is not None:
            # No free slot took it (all busy): hold it at the deferred
            # tail rather than dropping it — every deferred entry was
            # popped before anything still in the inbox, so this keeps
            # arrival order too.
            self._deferred.append(carry)
        return not self._deferred and self._inbox.empty()

    def _place_group(self, group: list[tuple[int, GenRequest]]) -> int:
        """Admit `group`; returns the number of prefill DEVICE DISPATCHES
        performed (the unit the per-block admission budget counts)."""
        # Requests the engine would reject (e.g. prompt beyond the largest
        # bucket) must fail individually, not poison the whole batch.
        wants_chunked = getattr(self.engine, "wants_chunked", None)
        lookup = getattr(self.engine, "prefix_lookup", None)
        align = getattr(self.engine, "prefix_align", None)
        seeded_ok = getattr(self.engine, "seeded_chunk_ok", None)
        now = time.monotonic()
        ready: list[tuple[int, GenRequest]] = []
        # Prefix-cache hits partition into their OWN dispatch units keyed
        # by (bucket, (radix node, matched_len)): equal keys share one
        # block-gather seed, and a hit unit admits through the engine's
        # cached path (pool gather + suffix-only prefill) while miss
        # units pay the full coalesced prefill — mixing them would force
        # everyone onto the slower path.
        hit_units: dict[tuple, tuple[Any, list[tuple[int, GenRequest]]]] = {}
        for slot, req in group:
            req.picked_at = now
            if req.ledger is not None:
                # Set-not-add: a budget deferral re-picks, and the
                # latest pick is the true scheduler queue wait.
                req.ledger.book_queue(now - req.enqueued_at)
            hit = None
            try:
                if req.adopt is not None:
                    # Handoff adoption (decode tier): parse the frame,
                    # fill req.prompt_ids, and seed the prefix store
                    # now, on THIS thread, so the lookup below hits it.
                    # Run exactly once — a budget-deferred request
                    # re-picks next block and must not re-adopt.
                    adopt, req.adopt = req.adopt, None
                    adopt(req)
                if not req.prompt_ids:
                    raise ValueError("empty prompt")
                n = len(req.prompt_ids)
                bucket = self.engine.bucket_for(n)
                hit = lookup(req.prompt_ids) if lookup is not None else None
                if hit is not None:
                    if n - hit.length <= align:
                        # Short suffix: batched single-dispatch hit path.
                        req.reused_tokens = hit.length
                        key = (bucket, hit.group_key)
                        if key in hit_units:
                            hit.release()  # one pinned handle per unit
                            hit_units[key][1].append((slot, req))
                        else:
                            hit_units[key] = (hit, [(slot, req)])
                        continue
                    if seeded_ok is not None and seeded_ok(n):
                        # Long suffix: chunked prefill seeded from the
                        # cached prefix (the engine releases the hit).
                        req.reused_tokens = hit.length
                        job = self.engine.start_chunked_prefill(
                            slot, req.prompt_ids, req.sampling, hit=hit)
                        hit = None
                        self._prefill_jobs.append((job, req))
                        continue
                    # No compiled continuation shape fits — full prefill.
                    hit.release()
                    hit = None
                    req.reused_tokens = 0
                if wants_chunked is not None and wants_chunked(n):
                    # Long prompt: build its prefix chunk-by-chunk between
                    # decode blocks instead of one monolithic dispatch.
                    job = self.engine.start_chunked_prefill(
                        slot, req.prompt_ids, req.sampling)
                    self._prefill_jobs.append((job, req))
                    continue
            except Exception as exc:  # noqa: BLE001
                if hit is not None:
                    hit.release()
                self._free.append(slot)
                self._emit_cb(req, TokenEvent(
                    text="", token_id=None, done=True, finish_reason="error",
                    error=str(exc)))
                continue
            ready.append((slot, req))
        if not ready and not hit_units:
            return 0
        # Partition by prefill bucket: the engine dispatches one coalesced
        # prefill per bucket, and mixing a long prompt into a short-prompt
        # group would drag every member into the long prompt's bucket
        # (batch × big-bucket = the exact transient the per-bucket batch
        # budget exists to bound). Each bucket subgroup is further split
        # to the bucket's batch cap HERE (not inside the engine) so every
        # device dispatch is individually counted and timed — the
        # admission budget and the admit metrics both depend on it.
        by_bucket: dict[int, list[tuple[int, GenRequest]]] = {}
        for slot, req in ready:
            by_bucket.setdefault(
                self.engine.bucket_for(len(req.prompt_ids)), []).append(
                    (slot, req))
        batches_for = getattr(self.engine, "prefill_batches_for", None)
        # Each unit: (subgroup, prefix hit or None), ordered by the
        # EARLIEST arrival among its members — under a tight admission
        # budget the unstarted tail of `units` defers to the next block,
        # so any other order (e.g. cheapest-first) would let a sustained
        # stream of late cache-hit arrivals starve an earlier deferred
        # miss, the exact FIFO inversion the deferred deque exists to
        # prevent.
        arrival = {id(req): i for i, (_s, req) in enumerate(group)}
        units: list[tuple[list[tuple[int, GenRequest]], Any]] = []
        for bucket_key, (hit, subgroup) in hit_units.items():
            cap = (max(batches_for(bucket_key[0]))
                   if batches_for is not None else len(subgroup))
            for start in range(0, len(subgroup), cap):
                # Split units share one pinned handle; release() is
                # idempotent and the handle's entry ref keeps the buffer
                # alive for the later splits either way.
                units.append((subgroup[start:start + cap], hit))
        for bucket, subgroup in by_bucket.items():
            cap = (max(batches_for(bucket)) if batches_for is not None
                   else len(subgroup))
            for start in range(0, len(subgroup), cap):
                units.append((subgroup[start:start + cap], None))
        units.sort(key=lambda u: min(arrival[id(req)] for _s, req in u[0]))
        n_dispatches = 0
        for unit_idx, (sub, hit) in enumerate(units):
            if (unit_idx > 0 and self._slots
                    and self._spent_this_block >= self._admit_budget_s):
                # The shared per-block time budget ran out mid-group: a
                # 16-request group spanning the 512 bucket splits into
                # 4-5 dispatches, and running them all back-to-back would
                # overshoot the budget several-fold and stall every
                # active stream. Defer the unstarted subgroups — slots
                # back to the pool, requests to the deferred queue (NOT
                # the inbox tail, which would put them behind later
                # arrivals and invert FIFO order every deferral) — and
                # let the next block pick them up. (unit_idx > 0
                # guarantees forward progress: one dispatch always lands.)
                # A deferred hit re-resolves through prefix_lookup next
                # block, so its pinned handle is released now.
                for d_sub, d_hit in units[unit_idx:]:
                    if d_hit is not None:
                        d_hit.release()
                    for slot, req in d_sub:
                        self._free.append(slot)
                        self._deferred.append(req)
                break
            t0m = time.monotonic()
            t0 = time.perf_counter()
            try:
                if hit is not None:
                    firsts = self.engine.prefill_and_insert_cached(
                        [(slot, req.prompt_ids, req.sampling)
                         for slot, req in sub], hit)
                elif len(sub) > 1:
                    firsts = self.engine.prefill_and_insert_many(
                        [(slot, req.prompt_ids, req.sampling)
                         for slot, req in sub])
                else:
                    slot0, req0 = sub[0]
                    firsts = [self.engine.prefill_and_insert(
                        slot0, req0.prompt_ids, req0.sampling)]
            except Exception as exc:  # noqa: BLE001 — engine errors → stream error
                n_dispatches += 1  # a failed dispatch still cost time
                self._spent_this_block += time.perf_counter() - t0
                for slot, req in sub:
                    self._free.append(slot)
                    log.error(
                        f"prefill failed for request {req.id}: {exc}")
                    self._emit_cb(req, TokenEvent(
                        text="", token_id=None, done=True,
                        finish_reason="error", error=str(exc)))
                continue
            dt = time.perf_counter() - t0
            n_dispatches += 1
            self._spent_this_block += dt
            if hit is not None and self._role == "decode":
                # Decode tier: a cached-unit dispatch is handoff ADOPTION
                # (seed copy + suffix), not admission prefill — book it
                # apart so this host's admit_* wall reads zero and the
                # trace row names the work. (A p==0 routing-only handoff
                # still full-prefills here and rightly counts as admit.)
                self.metrics["adopt_dispatches"] += 1
                self.metrics["adopt_s"] += dt
                self._adopt_hist.observe(dt)
                self.tracer.record("adopt_dispatch", t0m, dt, n=len(sub))
                self._m_dispatch.observe(dt, kind="adopt")
            else:
                self.metrics["admit_dispatches"] += 1
                self.metrics["admit_s"] += dt
                self._admit_hist.observe(dt)
                self.tracer.record("prefill_dispatch", t0m, dt, n=len(sub),
                                   cached=hit is not None)
                self._m_dispatch.observe(dt, kind="prefill")
            if self.ledger.enabled and dt > 0.0:
                # Prefill/adopt attribution is EXACT (the dispatch names
                # its requests): the unit wall splits across members by
                # suffix length, and a radix hit's avoided prefix is
                # priced at this very dispatch's per-token rate.
                phase = ("adopt" if hit is not None
                         and self._role == "decode" else "prefill")
                sfx = [max(1, len(req.prompt_ids) - req.reused_tokens)
                       for _s, req in sub]
                rate = dt / sum(sfx)
                for (slot_i, req), n_sfx in zip(sub, sfx):
                    if req.ledger is not None:
                        req.ledger.book_device(phase, rate * n_sfx)
                        if req.reused_tokens:
                            req.ledger.book_saved(
                                rate * req.reused_tokens,
                                req.reused_tokens)
            for (slot, req), first in zip(sub, firsts):
                self._activate(slot, req, first)
        return n_dispatches

    def _advance_prefills(self) -> None:
        """Run up to `prefill_chunks_per_block` prompt chunks, FIFO (the
        earliest request reaches its first token first). With no active
        streams there is nothing to stall, so drain faster."""
        if not self._prefill_jobs:
            return
        budget = (self._chunks_per_block if self._slots
                  else max(16, self._chunks_per_block))
        progressed = 0
        while budget > 0 and self._prefill_jobs:
            if (self._slots and progressed > 0
                    and self._spent_this_block >= self._admit_budget_s):
                # Shared per-block admission time budget exhausted — but
                # only AFTER at least one chunk ran: _admit_new always
                # lands at least one group per block, so without this
                # floor a sustained arrival stream would starve in-flight
                # chunked prefills (their TTFT growing unboundedly while
                # later short prompts keep being admitted).
                break
            job, req = self._prefill_jobs[0]
            if req.cancelled():
                self._prefill_jobs.pop(0)
                self._free.append(job.slot)
                if req.ledger is not None:
                    # Killed in-flight partial prefill: every chunk
                    # dispatched so far built a prefix nobody will
                    # decode from — the whole accumulated device time
                    # is waste.
                    req.ledger.waste_all_device("killed_prefill")
                self._emit_cb(req, TokenEvent(
                    text="", token_id=None, done=True,
                    finish_reason="cancelled"))
                continue
            t0m = time.monotonic()
            t0 = time.perf_counter()
            try:
                first = self.engine.advance_chunked_prefill(job)
            except Exception as exc:  # noqa: BLE001 — fail one, not all
                self._prefill_jobs.pop(0)
                self._free.append(job.slot)
                log.error(f"chunked prefill failed for {req.id}: {exc}")
                self._emit_cb(req, TokenEvent(
                    text="", token_id=None, done=True, finish_reason="error",
                    error=str(exc)))
                continue
            dt = time.perf_counter() - t0
            self.metrics["chunk_dispatches"] += 1
            self.metrics["chunk_s"] += dt
            self._spent_this_block += dt
            self.tracer.record("chunk_dispatch", t0m, dt,
                               request_id=req.id, trace_id=req.trace_id)
            self._m_dispatch.observe(dt, kind="chunk")
            if req.ledger is not None:
                req.ledger.book_device("chunk", dt)
            progressed += 1
            budget -= 1
            if first is not None:
                self._prefill_jobs.pop(0)
                if req.ledger is not None and req.reused_tokens:
                    # Seeded chunked prefill (radix hit with a long
                    # suffix): the avoided prefix is priced at this
                    # request's own measured chunk rate, known only now
                    # that the chunks have run.
                    req.ledger.book_saved_at_phase_rate(
                        "chunk",
                        len(req.prompt_ids) - req.reused_tokens,
                        req.reused_tokens)
                self._activate(job.slot, req, first)

    def _activate(self, slot: int, req: GenRequest, first: int) -> None:
        if req.resume_offset > 0 and req.reused_tokens > 0:
            # Booked HERE (activation runs exactly once per request, even
            # across budget deferrals that re-resolve the lookup): the
            # radix tokens this resume admission did not re-prefill.
            self.metrics["resume_reused_tokens"] += req.reused_tokens
            self._m_resume_reused.inc(req.reused_tokens)
        if self._role == "prefill":
            # Prefill tier: the request's KV is built and installed in
            # the slot lane — instead of decoding, hand it off and free
            # the lane. (The sampled `first` token is discarded: the
            # decode tier's suffix dispatch re-samples it from identical
            # logits — exact for greedy, seeded lanes re-derive the same
            # keys from their seed.)
            self._handoff_request(slot, req, first)
            return
        active = _ActiveSlot(req=req, decoder=self.engine.tokenizer.stream_decoder(),
                             prompt_len=len(req.prompt_ids))
        active.first_token_at = time.monotonic()
        self._ttft_hist.observe(active.first_token_at - req.enqueued_at)
        self._m_ttft.observe(active.first_token_at - req.enqueued_at)
        if self.tracer.enabled:
            # The request's admission phases as spans: scheduler-queue
            # wait (enqueue → placement pick) and prefill (pick → first
            # sampled token) — the engine-side legs of the per-stage TTFT
            # chain, now on the merged timeline too.
            picked = req.picked_at or active.first_token_at
            self.tracer.record("queue", req.enqueued_at,
                               picked - req.enqueued_at,
                               request_id=req.id, trace_id=req.trace_id)
            self.tracer.record("prefill", picked,
                               active.first_token_at - picked,
                               request_id=req.id, trace_id=req.trace_id,
                               prompt_len=len(req.prompt_ids))
        self._slots[slot] = active
        self.metrics["peak_occupancy"] = max(self.metrics["peak_occupancy"],
                                             len(self._slots))
        active.generated = 1
        if first in self.engine.tokenizer.eos_ids:
            self._finish(slot, active, "stop", first, ())
            return
        active.emitted = 1
        self.metrics["tokens"] += 1
        self._m_tokens.inc()
        # Finish before the first decode block if (a) the request's token
        # budget is already spent by the prefill token, or (b) the prompt is
        # so long the cache can't absorb the TWO dispatches that may land
        # before this slot's tokens are next examined (one in-flight + one
        # lookahead; each writes up to _max_block_writes positions) —
        # otherwise KV writes land past capacity (silently dropped
        # scatters) and the client would stream garbage.
        if (active.generated >= req.max_new_tokens
                or active.prompt_len + active.generated
                + 2 * self._max_block_writes
                > self.engine.slot_capacity + 1):
            self._finish(slot, active, "length", first, (first,))
            return
        if self._drafter is not None and req.speculative is not False:
            self._drafter.begin(slot, req.prompt_ids, first)
        self._submit_job(("first", active, first,
                          active.first_token_at - req.enqueued_at))

    def _handoff_request(self, slot: int, req: GenRequest,
                         first: int) -> None:
        """Prefill-tier terminal: serialize + ship the prompt's KV (the
        installed sink extracts the slot lane and writes the handoff
        frame synchronously — by return, the lane is re-usable), then
        free the slot. A sink failure fails THIS request with an error
        event; it must never kill the admission loop."""
        t0m = time.monotonic()
        try:
            self._handoff(slot, req, first)
        except Exception as exc:  # noqa: BLE001 — fail one, not all
            log.error(f"handoff failed for request {req.id}: {exc}")
            self._emit_cb(req, TokenEvent(
                text="", token_id=None, done=True, finish_reason="error",
                error=f"handoff failed: {exc}"))
        else:
            dt = time.monotonic() - t0m
            self.metrics["handoffs"] += 1
            self.metrics["handoff_s"] += dt
            self._m_handoffs.inc()
            if self.tracer.enabled:
                # Same per-request spans a unified host records (queue,
                # prefill), plus the handoff leg — the request's prefill-
                # tier residency reads off the merged timeline directly.
                picked = req.picked_at or t0m
                self.tracer.record("queue", req.enqueued_at,
                                   picked - req.enqueued_at,
                                   request_id=req.id, trace_id=req.trace_id)
                self.tracer.record("prefill", picked, t0m - picked,
                                   request_id=req.id, trace_id=req.trace_id,
                                   prompt_len=len(req.prompt_ids))
                self.tracer.record("handoff", t0m, dt,
                                   request_id=req.id, trace_id=req.trace_id)
        finally:
            self._free.append(slot)
            self.engine.release_slot(slot)
            if req.ledger is not None:
                # Prefill-tier terminal: the decode tier owns the finish
                # event; this host's attribution folds into aggregates.
                # Idempotent after the error path's finish() above.
                req.ledger.release("handoff")

    def _finish(self, slot: int, active: _ActiveSlot, reason: str,
                tok: int | None, run) -> None:
        """Terminal for an active slot. `run` is the token-id sequence
        (numpy slice or tuple) still to be pushed through the decoder
        ahead of the flush — the push itself is emit work and rides the
        finish job, off-thread while offload is on. Slot accounting
        (free list, engine release, drafter release, eviction counters)
        stays on the engine thread: the lane must be reusable by the
        very next admission pass."""
        ttft = (active.first_token_at - active.req.enqueued_at
                if active.first_token_at else None)
        if self.tracer.enabled and active.first_token_at is not None:
            self.tracer.record("generate", active.first_token_at,
                               time.monotonic() - active.first_token_at,
                               request_id=active.req.id,
                               trace_id=active.req.trace_id,
                               tokens=active.generated, finish=reason)
        costs = None
        if active.req.ledger is not None:
            costs = active.req.ledger.finish(reason,
                                             tokens=active.emitted)
            if self.tracer.enabled:
                # Per-request attribution counter tracks: cumulative
                # attributed/wasted device seconds stamped at every
                # finish — the Perfetto cost staircase, one ring append
                # pair per request lifetime.
                dev_t, waste_t = self.ledger.totals_brief()
                self.tracer.counter("ledger_device_s", round(dev_t, 6))
                self.tracer.counter("ledger_wasted_s", round(waste_t, 6))
        self._submit_job(("finish", active, run, tok, reason, ttft,
                          active.generated, active.emitted, costs))
        del self._slots[slot]
        self._free.append(slot)
        if self._drafter is not None:
            self._drafter.release(slot)
        self.engine.release_slot(slot)
        self.metrics["evictions"] += 1
        self._m_evictions.inc()

    def _emit(self, active: _ActiveSlot, ev: TokenEvent) -> None:
        """Queue a pre-built event for an active slot (stage decoration
        happens where the job runs, preserving per-request order)."""
        self._submit_job(("emit", active, ev))

    def _decorate(self, active: _ActiveSlot, ev: TokenEvent
                  ) -> tuple[GenRequest, TokenEvent]:
        if not active.stages_sent:
            # First event of the request: attach the per-stage admission
            # stamps (host recv → placement pick → first token). The host
            # adds its pipe-out stamp, the provider the relay stamp — the
            # full TTFT chain then reads out per stage in bench.py.
            # stages_sent is owned by whichever side runs the jobs
            # (exactly one; see _run_job).
            active.stages_sent = True
            ev.stages = {
                "recv": active.req.enqueued_at,
                "picked": active.req.picked_at or active.first_token_at,
                "first": active.first_token_at,
            }
            # First-event riders: the admission's radix reuse and — for
            # resumes — the completion offset generation continued from
            # (the relay's offset-dedup anchor).
            ev.tokens_reused = active.req.reused_tokens
            if active.req.resume_offset > 0:
                ev.resumed_from = active.req.resume_offset
        return active.req, ev

    def _emit_cb(self, req: GenRequest, ev: TokenEvent) -> None:
        """Queue a pre-built event with no slot attached (admission
        errors, queued cancels, deadline sheds). All job submissions
        happen on the engine thread, so the buffers need no lock.

        Terminal events close the request's cost account HERE — the one
        choke point every slotless exit path already goes through — so
        a request that sheds, errors, or cancels on ANY path still
        releases its ledger entry and ships its costs block (finish()
        is idempotent; a path that closed earlier books nothing twice)."""
        if ev.done and req.ledger is not None:
            ev.costs = req.ledger.finish(ev.finish_reason or "error")
        self._submit_job(("raw", req, ev))

    def _flush_events(self) -> None:
        """Block-boundary flush. Offload on: hand the buffered jobs to
        the emit worker as ONE bounded-queue put (blocking when the queue
        is full — the backpressure that bounds memory under a slow
        pipe). Offload off: deliver everything buffered inline — one
        emit_batch call when a sink is installed (→ one host-pipe frame
        per block), else per-event req.emit delivery."""
        if self._emit_offload:
            if self._block_jobs:
                jobs, self._block_jobs = self._block_jobs, []
                self._emit_queue.put(jobs)
            return
        if not self._pending_events:
            return
        batch, self._pending_events = self._pending_events, []
        self.metrics["emit_flushes"] += 1
        self.metrics["emit_events"] += len(batch)
        if self._emit_batch is not None:
            t0 = time.monotonic()
            try:
                self._emit_batch(batch)
            except Exception as exc:  # noqa: BLE001 — must never kill the loop
                log.error(f"emit batch sink failed: {exc}")
            dt = time.monotonic() - t0
            self.tracer.record("emit_flush", t0, dt, events=len(batch))
            if self.ledger.enabled and dt > 0.0:
                per = dt / len(batch)
                for req, _ev in batch:
                    if req.ledger is not None:
                        req.ledger.book_emit(per)
            return
        for req, ev in batch:
            try:
                req.emit(ev)
            except Exception as exc:  # noqa: BLE001 — emit must never kill the loop
                log.error(f"emit callback failed for request {req.id}: {exc}")

    def _check_invariants(self) -> None:
        active = set(self._slots)
        free = set(self._free)
        prefilling = {job.slot for job, _ in self._prefill_jobs}
        assert not (active & free), f"slot in both active and free: {active & free}"
        assert not (active & prefilling), \
            f"slot both active and prefilling: {active & prefilling}"
        assert not (free & prefilling), \
            f"slot both free and prefilling: {free & prefilling}"
        assert active | free | prefilling == set(range(self.engine.max_slots)), \
            "slot leak: some slot neither active, free, nor prefilling"
        for slot in active:
            assert self.engine.slot_length(slot) <= self.engine.slot_capacity


class AsyncSession:
    """Asyncio-side handle: submit a request, async-iterate token events."""

    def __init__(self, scheduler: Scheduler, *,
                 loop: asyncio.AbstractEventLoop | None = None) -> None:
        self._scheduler = scheduler
        self._loop = loop or asyncio.get_event_loop()
        self._queue: asyncio.Queue[TokenEvent] = asyncio.Queue()
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True

    def submit(self, prompt_ids: list[int], sampling: SamplingParams,
               max_new_tokens: int, request_id: str = "",
               speculative: bool | None = None,
               trace_id: str = "",
               deadline_s: float | None = None,
               resume_offset: int = 0) -> None:
        def emit(ev: TokenEvent) -> None:
            self._loop.call_soon_threadsafe(self._queue.put_nowait, ev)

        self._scheduler.submit(GenRequest(
            prompt_ids=prompt_ids, sampling=sampling,
            max_new_tokens=max_new_tokens, emit=emit,
            cancelled=lambda: self._cancelled, id=request_id,
            speculative=speculative, trace_id=trace_id,
            resume_offset=resume_offset,
            deadline_at=(time.monotonic() + deadline_s
                         if deadline_s is not None else None)))

    async def events(self):
        while True:
            ev = await self._queue.get()
            yield ev
            if ev.done:
                return
