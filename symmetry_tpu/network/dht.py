"""Kademlia-style DHT for decentralized provider discovery.

The reference's discovery floor is `hyperdht` (reference package-lock:
hyperdht 6.15.4 under hyperswarm): announce/lookup by 32-byte topic over a
Kademlia routing table, so providers are findable WITHOUT the central
server. This module is the TPU-era equivalent (SURVEY §2.2): the same
topic semantics — topic = discovery_key = BLAKE2b-32 of the provider's
public key (identity/identity.py) — over asyncio UDP datagrams.

Protocol (JSON datagrams, single round-trip request/response):

  ping          → pong                      liveness + routing-table refresh
  find_node(t)  → nodes closest to t        iterative lookup step
  announce(t)   → stored                    register (addr, pubkey) under t
  lookup(t)     → peers under t + nodes     discovery + further hops

Design choices vs the reference stack, deliberately simplified:
  - JSON over UDP instead of a custom binary codec — message sizes are
    tiny and this is the control plane, not the token stream.
  - Values (topic → peers) expire after TTL; announcers re-announce on an
    interval (REANNOUNCE_S), exactly hyperswarm's liveness model.
  - Announce/unannounce records that carry a publicKey are SIGNED with the
    announcer's Ed25519 key and verified on store: a third party can
    neither plant a record under someone else's key nor evict a live
    provider with a forged unannounce (hyperdht's mutable-record
    signing, here over the same identity key the data plane pins).
  - NAT holepunching lives one level up (network/natpunch.py,
    rendezvous-assisted simultaneous-open through the server); the DHT
    itself assumes reachable nodes (DC/DCN deployment).

Iterative lookup: standard Kademlia — query the ALPHA closest known nodes,
merge returned nodes, repeat until the closest set stabilizes, collect
peers from lookup responses along the way.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any

from symmetry_tpu.utils.logging import logger

K_BUCKET = 16          # max nodes per bucket (Kademlia k)
ALPHA = 3              # lookup parallelism
ID_BITS = 256
VALUE_TTL_S = 10 * 60  # announced peers expire unless re-announced
REANNOUNCE_S = 4 * 60
RPC_TIMEOUT_S = 2.0
# Wall-clock tolerance on signed records: announcer and storing node must
# agree within this window (10 min), or stores are rejected — signed
# discovery REQUIRES loosely NTP-synced clocks. A provider whose clock is
# skewed past this is undiscoverable on remote nodes; DHTNode escalates
# repeated all-rejected announce rounds to an error and exposes
# `consecutive_rejected_rounds` for health consumers (round-3 advisor).
MAX_SIG_SKEW_S = VALUE_TTL_S


def _xor_distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


def _announce_sig_msg(topic_hex: str, payload: dict, ts: float) -> bytes:
    """Canonical bytes an announcer signs: topic + payload (sans volatile
    fields) + wall-clock timestamp. Deterministic JSON so announcer and
    verifier serialize identically."""
    body = {k: v for k, v in payload.items() if k != "sig"}
    return json.dumps(["announce", topic_hex, body, round(ts, 3)],
                      sort_keys=True, separators=(",", ":")).encode()


def _unannounce_sig_msg(topic_hex: str, key: str, ts: float) -> bytes:
    return json.dumps(["unannounce", topic_hex, key, round(ts, 3)],
                      sort_keys=True, separators=(",", ":")).encode()


def parse_host_port(entry: str) -> tuple[str, int]:
    """'host:port' → (host, port) with a diagnosable error on bad input
    (shared by provider and client bootstrap-list parsing)."""
    host, sep, port = str(entry).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"DHT bootstrap entry {entry!r} must be 'host:port'")
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(
            f"DHT bootstrap entry {entry!r} has a non-numeric port") from None


@dataclass(slots=True)
class NodeInfo:
    node_id: bytes        # 32-byte DHT id
    host: str
    port: int
    last_seen: float = field(default_factory=time.monotonic)

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    def to_wire(self) -> list:
        return [self.node_id.hex(), self.host, self.port]

    @classmethod
    def from_wire(cls, raw: list) -> "NodeInfo":
        return cls(node_id=bytes.fromhex(raw[0]), host=raw[1],
                   port=int(raw[2]))


class RoutingTable:
    """256 XOR-distance buckets of up to K_BUCKET nodes each."""

    def __init__(self, self_id: bytes) -> None:
        self.self_id = self_id
        self.buckets: list[list[NodeInfo]] = [[] for _ in range(ID_BITS)]

    def _bucket_index(self, node_id: bytes) -> int:
        d = _xor_distance(self.self_id, node_id)
        return d.bit_length() - 1 if d else 0

    def add(self, node: NodeInfo) -> None:
        if node.node_id == self.self_id:
            return
        bucket = self.buckets[self._bucket_index(node.node_id)]
        for i, existing in enumerate(bucket):
            if existing.node_id == node.node_id:
                bucket[i] = node  # refresh address + last_seen
                return
        if len(bucket) < K_BUCKET:
            bucket.append(node)
        else:
            # Evict the stalest entry (reference hyperdht pings the oldest;
            # one-shot replacement keeps the table fresh without extra RPC)
            stalest = min(range(len(bucket)),
                          key=lambda i: bucket[i].last_seen)
            if bucket[stalest].last_seen + VALUE_TTL_S < time.monotonic():
                bucket[stalest] = node

    def remove(self, node_id: bytes) -> None:
        bucket = self.buckets[self._bucket_index(node_id)]
        bucket[:] = [n for n in bucket if n.node_id != node_id]

    def closest(self, target: bytes, count: int = K_BUCKET) -> list[NodeInfo]:
        everyone = [n for b in self.buckets for n in b]
        everyone.sort(key=lambda n: _xor_distance(n.node_id, target))
        return everyone[:count]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)


class _Protocol(asyncio.DatagramProtocol):
    def __init__(self, node: "DHTNode") -> None:
        self.node = node

    def datagram_received(self, data: bytes, addr) -> None:
        self.node._on_datagram(data, addr)


class DHTNode:
    """One DHT participant: routing table + topic store + RPC endpoint.

    Usage:
        node = DHTNode()
        await node.start("127.0.0.1", 0, bootstrap=[(host, port), ...])
        await node.announce(topic, payload={"address": ..., "publicKey": ...})
        peers = await node.lookup(topic)
    """

    def __init__(self, node_id: bytes | None = None, *,
                 identity=None) -> None:
        self.node_id = node_id or os.urandom(32)
        # Optional Ed25519 identity (identity/identity.py). When set,
        # announce()/unannounce() sign their records so remote nodes can
        # verify them against the payload's publicKey.
        self.identity = identity
        self.table = RoutingTable(self.node_id)
        # topic hex -> {peer key -> (payload, stored_at)}
        self._store: dict[str, dict[str, tuple[dict, float]]] = {}
        # (topic hex, key) -> signed unannounce ts: fences REPLAYED
        # announces — without it, a captured announce packet re-stored
        # after the owner's unannounce resurrects a drained provider.
        self._tombstones: dict[tuple[str, str], float] = {}
        self._transport: asyncio.DatagramTransport | None = None
        self._pending: dict[str, asyncio.Future] = {}
        self._seq = 0
        self._announcing: dict[str, dict] = {}
        self._tasks: set[asyncio.Task] = set()
        # Announce rounds in a row where every reachable node rejected the
        # record and none stored it (clock skew / bad signature). See
        # is_discoverable / _announce_once.
        self.consecutive_rejected_rounds = 0

    # Fully-rejected announce rounds tolerated before this node is
    # considered undiscoverable (health error + is_discoverable False).
    REJECTED_ROUNDS_UNHEALTHY = 2

    @property
    def is_discoverable(self) -> bool:
        """False once repeated announce rounds were fully rejected — the
        single health predicate consumed by provider.stats() and the
        escalation log (keep them in sync by construction)."""
        return (self.consecutive_rejected_rounds
                < self.REJECTED_ROUNDS_UNHEALTHY)

    # ------------------------------------------------------------ lifecycle

    async def start(self, host: str = "0.0.0.0", port: int = 0,
                    bootstrap: list[tuple[str, int]] | None = None) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Protocol(self), local_addr=(host, port))
        reached = False
        for addr in bootstrap or []:
            try:
                await self._rpc(addr, {"type": "ping"})
                reached = True
            except asyncio.TimeoutError:
                logger.warning(f"dht bootstrap node {addr} unreachable")
        if reached:
            # one table-population lookup around our own id, after all
            # bootstrap pings (not one full lookup per bootstrap node)
            await self._iterative_find(self.node_id)
        task = asyncio.get_running_loop().create_task(self._maintenance())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    @property
    def port(self) -> int:
        assert self._transport is not None
        return self._transport.get_extra_info("sockname")[1]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._transport is not None:
            self._transport.close()

    # ------------------------------------------------------------ public API

    async def announce(self, topic: bytes, payload: dict) -> int:
        """Store (self, payload) under topic on the closest nodes; returns
        the number of nodes that accepted. Re-announced periodically until
        unannounce(). Records are keyed by the payload's publicKey when
        present, so a restarted announcer OVERWRITES its old record rather
        than leaving a stale twin under a fresh DHT node id.

        publicKey records are SIGNED (the node's identity must hold that
        key): remote nodes verify on store, so nobody can announce under —
        or later unannounce — a key they don't control."""
        if self.identity is not None:
            payload = dict(payload)
            payload.setdefault("publicKey", self.identity.public_hex)
        if payload.get("publicKey") and (
                self.identity is None
                or self.identity.public_hex != payload["publicKey"]):
            raise ValueError(
                "announcing a publicKey record requires the matching "
                "identity to sign it (DHTNode(identity=...))")
        self._announcing[topic.hex()] = payload
        return await self._announce_once(topic, payload)

    async def unannounce(self, topic: bytes) -> None:
        """Stop re-announcing AND delete the record from the remote nodes
        holding it (hyperdht semantics) — without the RPC, a drained
        provider would stay resolvable until TTL expiry (~10 min). Signed
        when the record was, so third parties can't evict it."""
        payload = self._announcing.pop(topic.hex(), None)
        key = self._record_key(payload or {})
        self._store.get(topic.hex(), {}).pop(key, None)
        msg: dict[str, Any] = {"type": "unannounce", "topic": topic.hex(),
                               "key": key}
        if self.identity is not None and key == self.identity.public_hex:
            ts = time.time()
            msg["ts"] = round(ts, 3)
            msg["sig"] = self.identity.sign(
                _unannounce_sig_msg(topic.hex(), key, ts)).hex()
        # One retry on timeout: a node that misses the unannounce also
        # misses the replay-fencing tombstone, so a captured announce could
        # be replayed at it for up to MAX_SIG_SKEW_S (best-effort fence —
        # nodes unreachable through both attempts keep that residual
        # window, bounded by the record TTL).
        for node in self.table.closest(topic, K_BUCKET):
            for _ in range(2):
                try:
                    await self._rpc(node.addr, msg)
                    break
                except asyncio.TimeoutError:
                    continue

    def _record_key(self, payload: dict) -> str:
        return str(payload.get("publicKey") or self.node_id.hex())

    async def lookup(self, topic: bytes) -> list[dict]:
        """Find peers announced under topic anywhere in the DHT."""
        peers: dict[str, dict] = {}
        # local hits first
        for key, (payload, _) in self._store.get(topic.hex(), {}).items():
            peers[key] = payload
        await self._iterative_find(topic, collect_peers=peers)
        return list(peers.values())

    # ------------------------------------------------------------ internals

    async def _announce_once(self, topic: bytes, payload: dict) -> int:
        if self.identity is not None and payload.get("publicKey"):
            # Fresh timestamp + signature per (re-)announce: the ts also
            # fences unannounce replays from before the latest announce.
            payload = {k: v for k, v in payload.items()
                       if k not in ("sig", "ts")}
            ts = time.time()
            payload["ts"] = round(ts, 3)
            payload["sig"] = self.identity.sign(
                _announce_sig_msg(topic.hex(), payload, ts)).hex()
        await self._iterative_find(topic)
        targets = self.table.closest(topic, K_BUCKET) or []
        ok = 0
        rejected = 0
        for node in targets[:K_BUCKET]:
            try:
                resp = await self._rpc(node.addr, {
                    "type": "announce", "topic": topic.hex(),
                    "payload": payload})
                # A "rejected" reply (bad signature / clock skew) is NOT a
                # store — counting it would log "announced on N nodes"
                # while the provider is undiscoverable.
                if resp.get("type") == "stored":
                    ok += 1
                else:
                    rejected += 1
                    logger.warning(
                        f"dht announce rejected by {node.addr}: "
                        f"{resp.get('error', resp.get('type'))}")
            except asyncio.TimeoutError:
                self.table.remove(node.node_id)
        # Every reachable node rejecting while none stores is a HEALTH
        # condition, not noise: the classic cause is a skewed local clock
        # (> MAX_SIG_SKEW_S), which leaves this announcer silently
        # undiscoverable while its own log shows routine re-announces.
        if rejected and not ok:
            self.consecutive_rejected_rounds += 1
            if not self.is_discoverable:
                logger.error(
                    f"dht: {self.consecutive_rejected_rounds} consecutive "
                    f"announce rounds fully rejected — this node is NOT "
                    f"discoverable. Most likely cause: local clock skewed "
                    f"more than {MAX_SIG_SKEW_S / 60:.0f} min from the "
                    f"storing nodes (signed records require NTP-synced "
                    f"clocks)")
        elif ok:
            self.consecutive_rejected_rounds = 0
        # Always store locally too: a 1-node network must still resolve.
        self._store_value(topic.hex(), self._record_key(payload), payload)
        return ok

    async def _iterative_find(self, target: bytes,
                              collect_peers: dict | None = None) -> None:
        queried: set[bytes] = set()
        shortlist = self.table.closest(target, K_BUCKET)
        while True:
            batch = [n for n in shortlist if n.node_id not in queried][:ALPHA]
            if not batch:
                return
            results = await asyncio.gather(
                *(self._find_rpc(n, target, collect_peers) for n in batch),
                return_exceptions=True)
            for node, res in zip(batch, results):
                queried.add(node.node_id)
                if isinstance(res, Exception):
                    self.table.remove(node.node_id)
            shortlist = self.table.closest(target, K_BUCKET)

    async def _find_rpc(self, node: NodeInfo, target: bytes,
                        collect_peers: dict | None) -> None:
        msg_type = "lookup" if collect_peers is not None else "find_node"
        resp = await self._rpc(node.addr, {"type": msg_type,
                                           "topic": target.hex()})
        for raw in resp.get("nodes", []):
            try:
                self.table.add(NodeInfo.from_wire(raw))
            except (ValueError, IndexError, TypeError):
                continue
        if collect_peers is not None:
            for key, payload in resp.get("peers", {}).items():
                collect_peers.setdefault(key, payload)

    def _store_value(self, topic_hex: str, key: str, payload: dict) -> None:
        self._store.setdefault(topic_hex, {})[key] = (payload, time.monotonic())

    async def _maintenance(self) -> None:
        while True:
            await asyncio.sleep(REANNOUNCE_S)
            now = time.monotonic()
            for topic_hex, entries in list(self._store.items()):
                for key, (_, stored) in list(entries.items()):
                    if stored + VALUE_TTL_S < now:
                        del entries[key]
                if not entries:
                    del self._store[topic_hex]
            # Tombstones only need to outlive the announce-replay window
            # (announces older than MAX_SIG_SKEW_S are rejected anyway).
            cutoff = time.time() - 2 * MAX_SIG_SKEW_S
            self._tombstones = {k: ts for k, ts in self._tombstones.items()
                                if ts > cutoff}
            for topic_hex, payload in list(self._announcing.items()):
                try:
                    await self._announce_once(bytes.fromhex(topic_hex),
                                              payload)
                except Exception as exc:  # noqa: BLE001 — keep re-announcing
                    logger.debug(f"dht re-announce failed: {exc}")

    # ------------------------------------------------------------ wire

    async def _rpc(self, addr: tuple[str, int], msg: dict) -> dict:
        self._seq += 1
        msg_id = f"{self._seq}:{os.urandom(4).hex()}"
        msg = {**msg, "id": msg_id,
               "from": [self.node_id.hex(), self.port]}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            assert self._transport is not None, "node not started"
            self._transport.sendto(json.dumps(msg).encode(), addr)
            return await asyncio.wait_for(fut, RPC_TIMEOUT_S)
        finally:
            self._pending.pop(msg_id, None)

    def _on_datagram(self, data: bytes, addr: tuple[str, int]) -> None:
        try:
            msg = json.loads(data.decode())
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(msg, dict):
            return
        msg_id = msg.get("id")
        sender = msg.get("from")
        if isinstance(sender, list) and len(sender) == 2:
            try:
                self.table.add(NodeInfo(node_id=bytes.fromhex(sender[0]),
                                        host=addr[0], port=int(sender[1])))
            except (ValueError, TypeError):
                pass
        if msg.get("resp"):
            fut = self._pending.get(msg_id or "")
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return
        reply = self._handle_request(msg)
        if reply is not None and self._transport is not None:
            reply.update(id=msg_id, resp=True,
                         **{"from": [self.node_id.hex(), self.port]})
            self._transport.sendto(json.dumps(reply).encode(), addr)

    def _handle_request(self, msg: dict) -> dict | None:
        mtype = msg.get("type")
        if mtype == "ping":
            return {"type": "pong"}
        if mtype in ("find_node", "lookup"):
            topic_hex = msg.get("topic", "")
            try:
                target = bytes.fromhex(topic_hex)
            except ValueError:
                return None
            nodes = [n.to_wire() for n in self.table.closest(target, K_BUCKET)]
            reply: dict[str, Any] = {"type": "nodes", "nodes": nodes}
            if mtype == "lookup":
                reply["peers"] = {
                    key: payload for key, (payload, _)
                    in self._store.get(topic_hex, {}).items()}
            return reply
        if mtype == "announce":
            topic_hex = msg.get("topic", "")
            payload = msg.get("payload")
            sender = msg.get("from")
            if (isinstance(payload, dict) and isinstance(sender, list)
                    and len(topic_hex) == 64):
                # Key by the announced publicKey (falling back to the DHT
                # node id): a restarted announcer overwrites its old
                # record instead of accumulating stale twins. publicKey
                # records must carry a valid fresh signature under that
                # key — otherwise anyone could shadow a provider's record.
                if payload.get("publicKey"):
                    if not self._verify_announce(topic_hex, payload):
                        return {"type": "rejected", "error": "bad signature"}
                    key = str(payload["publicKey"])
                    # Replay fence: an announce signed BEFORE the owner's
                    # last verified unannounce must not resurrect the record.
                    dead_ts = self._tombstones.get((topic_hex, key))
                    if (dead_ts is not None
                            and float(payload.get("ts", 0)) <= dead_ts):
                        return {"type": "rejected", "error": "tombstoned"}
                else:
                    # sender[0] is the announcer's DHT node id (the "from"
                    # field is [node_id_hex, port]) — the same fallback
                    # _record_key uses, so its unannounce key matches.
                    key = str(sender[0])
                self._store_value(topic_hex, key, payload)
                return {"type": "stored"}
            return None
        if mtype == "unannounce":
            topic_hex = msg.get("topic", "")
            key = str(msg.get("key", ""))
            entries = self._store.get(topic_hex, {})
            existing = entries.get(key)
            if existing is not None and existing[0].get("publicKey"):
                # Signed record: removal needs a fresh signature under the
                # SAME key, timestamped at/after the stored announce — a
                # forged or replayed unannounce can't evict a live
                # provider. (Round-2 verdict: discovery-DoS hole.)
                if not self._verify_unannounce(topic_hex, key, msg,
                                               existing[0]):
                    return {"type": "rejected", "error": "bad signature"}
                self._tombstones[(topic_hex, key)] = float(msg.get("ts", 0))
            entries.pop(key, None)
            return {"type": "removed"}
        return None

    def _verify_announce(self, topic_hex: str, payload: dict) -> bool:
        from symmetry_tpu.identity import Identity

        try:
            pub = bytes.fromhex(str(payload["publicKey"]))
            sig = bytes.fromhex(str(payload.get("sig", "")))
            ts = float(payload.get("ts", 0))
        except (ValueError, TypeError):
            return False
        if abs(time.time() - ts) > MAX_SIG_SKEW_S:
            return False
        return Identity.verify(
            _announce_sig_msg(topic_hex, payload, ts), sig, pub)

    @staticmethod
    def _verify_unannounce(topic_hex: str, key: str, msg: dict,
                           stored: dict) -> bool:
        from symmetry_tpu.identity import Identity

        try:
            pub = bytes.fromhex(key)
            sig = bytes.fromhex(str(msg.get("sig", "")))
            ts = float(msg.get("ts", 0))
        except (ValueError, TypeError):
            return False
        if abs(time.time() - ts) > MAX_SIG_SKEW_S:
            return False
        if ts < float(stored.get("ts", 0)):
            return False  # replay from before the latest announce
        return Identity.verify(
            _unannounce_sig_msg(topic_hex, key, ts), sig, pub)
