"""Peer: an authenticated, encrypted, message-oriented connection.

The unit the whole control plane speaks over — equivalent to the reference's
hyperswarm `Peer` (src/types.ts:124-180: noise-encrypted duplex with
publicKey + write/on('data')), but message-framed and with enforced mutual
authentication (the reference's verification is advisory-only,
src/provider.ts:157-167).

    peer = await Peer.connect(conn, identity, initiator=True)
    await peer.send(MessageKey.PING)
    async for msg in peer:            # Message(key=..., data=...)
        ...
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from symmetry_tpu.identity import (
    Identity,
    SecureSession,
    client_handshake,
    discovery_key,
    server_handshake,
)
from symmetry_tpu.protocol.framing import FrameError
from symmetry_tpu.protocol.messages import Message, create_message, parse_message
from symmetry_tpu.transport.base import Connection
from symmetry_tpu.utils.logging import logger


class Peer:
    def __init__(self, conn: Connection, session: SecureSession) -> None:
        self._conn = conn
        self._session = session
        self.raw_bytes_written = 0  # wire counters, reference src/types.ts:148-149
        self.raw_bytes_read = 0

    @classmethod
    async def connect(
        cls,
        conn: Connection,
        identity: Identity,
        *,
        initiator: bool,
        expected_remote_key: bytes | None = None,
    ) -> "Peer":
        """Run the handshake; on any auth failure the connection is closed and
        the HandshakeError propagates (never stay connected unauthenticated)."""
        try:
            if initiator:
                session = await client_handshake(conn, identity, expected_remote_key)
            else:
                session = await server_handshake(conn, identity, expected_remote_key)
        except Exception:
            await conn.close()
            raise
        return cls(conn, session)

    @property
    def remote_public_key(self) -> bytes:
        return self._session.remote_public_key

    @property
    def remote_public_hex(self) -> str:
        return self._session.remote_public_key.hex()

    @property
    def remote_discovery_key(self) -> bytes:
        return discovery_key(self._session.remote_public_key)

    @property
    def remote_address(self) -> str:
        return self._conn.remote_address

    @property
    def closed(self) -> bool:
        return self._conn.closed

    @property
    def write_stats(self) -> dict | None:
        """The transport's emit-path write counters (transport/base.py
        WriteCork), when it tracks them."""
        return self._conn.write_stats

    async def send(self, key: str, data: Any = None) -> None:
        payload = create_message(key, data)
        ct = self._session.encrypt(payload)
        self.raw_bytes_written += len(ct)
        await self._conn.send(ct)

    async def send_raw(self, payload: bytes) -> None:
        """Send pre-encoded message bytes (hot path: token chunks)."""
        ct = self._session.encrypt(payload)
        self.raw_bytes_written += len(ct)
        await self._conn.send(ct)

    async def recv(self) -> Message | None:
        """Next message, or None on EOF. Malformed messages are skipped."""
        while True:
            try:
                frame = await self._conn.recv()
            except (FrameError, ConnectionError, OSError) as exc:
                logger.warning(f"dropping peer {self.remote_public_hex[:12]}: {exc}")
                await self.close()
                return None
            if frame is None:
                return None
            self.raw_bytes_read += len(frame)
            try:
                payload = self._session.decrypt(frame)
            except Exception as exc:
                logger.warning(f"dropping peer {self.remote_public_hex[:12]}: {exc}")
                await self.close()
                return None
            msg = parse_message(payload)
            if msg is None:
                logger.debug("skipping malformed message from", self.remote_public_hex[:12])
                continue
            return msg

    def __aiter__(self) -> AsyncIterator[Message]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[Message]:
        while True:
            msg = await self.recv()
            if msg is None:
                return
            yield msg

    async def close(self) -> None:
        await self._conn.close()
