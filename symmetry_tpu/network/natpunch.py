"""NAT traversal: rendezvous-assisted UDP hole punching for udpstream.

The reference's providers are reachable behind NAT via hyperdht's
holepunching (dep hyperdht 6.15.4; swarm join at reference
src/provider.ts:38-49 — the capability its readme's architecture sells).
This is the TPU-era equivalent over our native transport:

  - Every udpstream ctx (native/udpstream/udpstream.cpp) carries an F_RAW
    side channel: connectionless datagrams from the SAME socket the
    stream protocol uses, so a raw packet opens exactly the NAT mapping
    a later stream will traverse (transport/udp.py RawChannel).
  - A rendezvous service (PunchRendezvous, typically co-located with the
    Symmetry server) observes each provider's REFLEXIVE address from its
    periodic `register` datagrams.
  - A client asks the rendezvous for a provider (`request`); the
    rendezvous tells the client the provider's reflexive address (`peer`)
    and simultaneously tells the provider the client's (`invite`).
  - Both sides burst `punch` datagrams at each other: each burst opens
    the sender's own NAT pinhole outward, so the other side's packets —
    and then the client's us_dial SYNs — pass. Simultaneous-open is safe
    at the stream layer: an inbound SYN on a dialing ctx just queues a
    connection that is never accepted.
  - The client then dials `udp://reflexive` from the SAME ctx/port.

Wire format: JSON payloads in F_RAW frames. All messages are small and
connectionless; loss is handled by repetition (register re-sends on an
interval, request retries, punches burst).

Relay fallback (provider unreachable even after punching) lives at the
protocol layer instead: server-spliced end-to-end-encrypted relay
(server/broker.py RELAY_* keys) — see network/relay.py.
"""

from __future__ import annotations

import asyncio
import json
import struct
import time
from typing import Any

from symmetry_tpu.utils.logging import logger

# F_RAW frame header (must match native/udpstream/udpstream.cpp pack_hdr):
# MAGIC, flags, conn_id u32, seq u32, ack u32, len u16 → 16 bytes.
_MAGIC = 0xD5
_F_RAW = 32
_HDR = struct.Struct("<BBIII H")  # 1+1+4+4+4+2 = 16

PUNCH_BURST = 6
PUNCH_INTERVAL_S = 0.25
REGISTER_INTERVAL_S = 20.0
ENTRY_TTL_S = 90.0


def wrap_raw(payload: bytes) -> bytes:
    """Frame a payload exactly like us_send_raw does — lets a plain
    asyncio UDP socket (the rendezvous) interoperate with udpstream's
    raw channel."""
    return _HDR.pack(_MAGIC, _F_RAW, 0, 0, 0, len(payload)) + payload


def unwrap_raw(packet: bytes) -> bytes | None:
    if len(packet) < _HDR.size:
        return None
    magic, flags, _, _, _, ln = _HDR.unpack_from(packet)
    if magic != _MAGIC or not flags & _F_RAW:
        return None
    return packet[_HDR.size:_HDR.size + ln]


def _msg(op: str, **kw: Any) -> bytes:
    return json.dumps({"op": op, **kw}).encode()


def resolve_endpoint(addr: tuple[str, int]) -> tuple[str, int]:
    """Resolve a (host, port) to an IPv4 literal once, up front: the raw
    channel (us_send_raw) takes only literals, and invite/peer source
    matching compares against inet_ntop output — a hostname would make
    both fail silently."""
    import socket

    host, port = addr
    try:
        return socket.gethostbyname(host), int(port)
    except OSError as exc:
        raise ConnectionError(
            f"cannot resolve rendezvous host {host!r}: {exc}") from exc


MAX_REGISTRY = 4096
REGISTER_SKEW_S = 90.0
# Source-address proof for `request` (round-3 advisor): UDP sources are
# spoofable, so an unauthenticated request would let an attacker point a
# provider's 6-packet punch burst at a victim (small reflection vector)
# and learn reflexive addresses. A requester must first echo a stateless
# cookie (keyed hash of its source address + time window) — proving it
# RECEIVES at the claimed source — before the rendezvous brokers a punch.
COOKIE_WINDOW_S = 30.0
# Per-source invite budget: even a cookie-proven source can't grind a
# provider with endless punch bursts.
MAX_INVITES_PER_SOURCE = 8
INVITE_WINDOW_S = 30.0
# Retransmissions of the same (source, key) dial within this window are
# answered but charged to the invite budget only once.
DIAL_DEDUP_S = 10.0


def _register_sig_msg(key_hex: str, ts: float) -> bytes:
    return json.dumps(["punch-register", key_hex, round(ts, 3)],
                      sort_keys=True, separators=(",", ":")).encode()


class PunchRendezvous:
    """The server-side endpoint: learns reflexive addresses, brokers
    punches. Plain asyncio UDP speaking F_RAW frames.

    Registrations are SIGNED with the provider's Ed25519 key (the same
    identity the data plane pins): provider keys are public, so an
    unsigned rendezvous would let anyone overwrite a provider's
    reflexive address and deny NAT traversal to it — the same spoofing
    class the DHT's signed announces close."""

    def __init__(self) -> None:
        import os

        self._registry: dict[str, tuple[tuple[str, int], float]] = {}
        # replay fence: last accepted signed ts per key — a captured
        # register datagram re-sent from another address must not move
        # the record
        self._last_ts: dict[str, float] = {}
        self._transport: asyncio.DatagramTransport | None = None
        self._cookie_secret = os.urandom(16)
        self._invites: dict[tuple[str, int], list[float]] = {}
        # (source addr, target key) -> last brokered ts (retransmission
        # dedup for the invite budget; see the `request` handler)
        self._recent_dials: dict[tuple[tuple[str, int], str], float] = {}

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> None:
        loop = asyncio.get_running_loop()

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(proto, data: bytes, addr) -> None:
                self._on_datagram(data, addr)

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(host, port))

    @property
    def port(self) -> int:
        assert self._transport is not None
        return self._transport.get_extra_info("sockname")[1]

    async def stop(self) -> None:
        if self._transport is not None:
            self._transport.close()

    def _send(self, payload: bytes, addr: tuple[str, int]) -> None:
        assert self._transport is not None
        self._transport.sendto(wrap_raw(payload), addr)

    def _on_datagram(self, data: bytes, addr: tuple[str, int]) -> None:
        payload = unwrap_raw(data)
        if payload is None:
            return
        try:
            msg = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            return
        op = msg.get("op")
        if op == "register":
            key = str(msg.get("key", ""))[:128]
            if key and self._verify_register(key, msg):
                ts = float(msg.get("ts", 0))
                if ts <= self._last_ts.get(key, 0.0):
                    return  # replayed or out-of-order register
                if len(self._registry) >= MAX_REGISTRY:
                    now = time.monotonic()
                    self._registry = {
                        k: v for k, v in self._registry.items()
                        if v[1] + ENTRY_TTL_S > now}
                    self._last_ts = {k: t for k, t in self._last_ts.items()
                                     if k in self._registry}
                if len(self._registry) < MAX_REGISTRY:
                    self._last_ts[key] = ts
                    self._registry[key] = (addr, time.monotonic())
                    self._send(_msg("registered", addr=list(addr)), addr)
        elif op == "request":
            key = str(msg.get("key", ""))
            if not self._cookie_ok(str(msg.get("cookie", "")), addr):
                # Source unproven: answer with a cookie only. A spoofed
                # source never sees this reply, so it can never present
                # the cookie — no burst is ever pointed at a bystander.
                self._send(_msg("challenge", key=key,
                                cookie=self._cookie_for(addr)), addr)
                return
            entry = self._registry.get(key)
            if entry is None or entry[1] + ENTRY_TTL_S < time.monotonic():
                self._send(_msg("unknown", key=key), addr)
                return
            # Budget accounting: unknown-key probes never charge (the
            # lookup above short-circuits), and RETRANSMISSIONS of the
            # same (source, key) dial within a short window charge only
            # once — punch_dial resends every second while replies are
            # lost, one persistent dial socket serves all of a client's
            # dials (transport/udp.py), and charging each resend would
            # burn the whole window on a single lossy dial.
            now_m = time.monotonic()
            dial_key = (addr, key)
            last = self._recent_dials.get(dial_key, -1e9)
            is_retransmit = now_m - last < DIAL_DEDUP_S
            # Keep the FIRST-seen time: refreshing on every resend would
            # let a proven source stay "retransmitting" forever and never
            # be charged to the invite budget. With first-seen semantics a
            # sustained resender is re-charged once per DIAL_DEDUP_S
            # window, so MAX_INVITES_PER_SOURCE actually bounds the punch
            # bursts it can aim at a provider.
            if not is_retransmit:
                self._recent_dials[dial_key] = now_m
            if len(self._recent_dials) > MAX_REGISTRY:
                self._recent_dials = {
                    k: t for k, t in self._recent_dials.items()
                    if now_m - t < DIAL_DEDUP_S}
            if not is_retransmit and not self._invite_allowed(addr):
                # Proven source, but over its punch budget. Reply
                # explicitly (safe — the source is cookie-proven) so the
                # dialer fails fast instead of resending into silence.
                self._send(_msg("busy", key=key), addr)
                return
            target_addr = entry[0]
            # Tell the requester where the target is, AND the target where
            # the requester is — both start punching at once.
            self._send(_msg("peer", key=key, addr=list(target_addr)), addr)
            self._send(_msg("invite", addr=list(addr)), target_addr)
        # "punch"/"registered"/"peer"/"invite" arriving here are strays

    def _cookie_for(self, addr: tuple[str, int],
                    window_off: int = 0) -> str:
        import hashlib

        window = int(time.time() // COOKIE_WINDOW_S) + window_off
        return hashlib.blake2b(
            f"{addr[0]}|{addr[1]}|{window}".encode(),
            key=self._cookie_secret, digest_size=16).hexdigest()

    def _cookie_ok(self, cookie: str, addr: tuple[str, int]) -> bool:
        import hmac

        if not cookie:
            return False
        # current or previous window: a cookie issued just before a
        # window boundary must not bounce its echo
        return any(hmac.compare_digest(cookie, self._cookie_for(addr, off))
                   for off in (0, -1))

    def _invite_allowed(self, addr: tuple[str, int]) -> bool:
        now = time.monotonic()
        if len(self._invites) >= MAX_REGISTRY:  # bound the tracker itself
            self._invites = {
                a: ts for a, ts in self._invites.items()
                if ts and now - ts[-1] < INVITE_WINDOW_S}
        recent = [t for t in self._invites.get(addr, [])
                  if now - t < INVITE_WINDOW_S]
        if len(recent) >= MAX_INVITES_PER_SOURCE:
            self._invites[addr] = recent
            return False
        recent.append(now)
        self._invites[addr] = recent
        return True

    @staticmethod
    def _verify_register(key_hex: str, msg: dict) -> bool:
        from symmetry_tpu.identity import Identity

        try:
            pub = bytes.fromhex(key_hex)
            sig = bytes.fromhex(str(msg.get("sig", "")))
            ts = float(msg.get("ts", 0))
        except (ValueError, TypeError):
            return False
        if abs(time.time() - ts) > REGISTER_SKEW_S:
            return False
        return Identity.verify(_register_sig_msg(key_hex, ts), sig, pub)


class ProviderPuncher:
    """Provider-side worker: keeps the provider registered at the
    rendezvous (through its LISTENER ctx, so the reflexive address maps
    the stream port) and answers invites with punch bursts."""

    def __init__(self, raw_channel, rendezvous: tuple[str, int],
                 identity) -> None:
        self._raw = raw_channel
        self._rdv = resolve_endpoint(rendezvous)
        self._identity = identity
        self._key = identity.public_hex
        self._task: asyncio.Task | None = None
        self.punched: int = 0  # invites answered (introspection/tests)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _run(self) -> None:
        next_register = 0.0
        while True:
            now = time.monotonic()
            if now >= next_register:
                ts = time.time()
                sig = self._identity.sign(
                    _register_sig_msg(self._key, ts)).hex()
                if not self._raw.send(
                        self._rdv[0], self._rdv[1],
                        _msg("register", key=self._key,
                             ts=round(ts, 3), sig=sig)):
                    logger.warning(
                        f"punch register send to {self._rdv} failed")
                next_register = now + REGISTER_INTERVAL_S
            got = await self._raw.recv(timeout_s=1.0)
            if got is None:
                continue
            payload, host, port = got
            msg = _parse(payload)
            if msg is None:
                continue
            if msg.get("op") == "invite" and (host, port) == self._rdv:
                addr = msg.get("addr") or []
                if len(addr) == 2:
                    self.punched += 1
                    # burst concurrently: serial bursts (1.5 s each) would
                    # stall invite handling for later clients past their
                    # punch deadline
                    task = asyncio.get_running_loop().create_task(
                        self._burst(str(addr[0]), int(addr[1])))
                    task.add_done_callback(lambda t: t.exception())
            # punches from clients need no reply: their arrival already
            # proves our pinhole is open, and ours open theirs

    async def _burst(self, host: str, port: int) -> None:
        for _ in range(PUNCH_BURST):
            self._raw.send(host, port, _msg("punch", key=self._key))
            await asyncio.sleep(PUNCH_INTERVAL_S)


def _parse(payload: bytes) -> dict | None:
    try:
        msg = json.loads(payload.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    return msg if isinstance(msg, dict) else None


async def punch_dial(transport, rendezvous: tuple[str, int],
                     target_key_hex: str, *,
                     timeout_s: float = 8.0) -> str:
    """Client side: resolve + punch a provider through the rendezvous;
    returns the dialable `udp://host:port` address. The caller then dials
    it with the SAME transport — the dial leaves from the ctx whose
    pinhole the punches opened.

    Raises ConnectionError when the rendezvous doesn't know the key or
    nothing gets through within the timeout.
    """
    rendezvous = resolve_endpoint(rendezvous)
    raw = transport.dial_raw_channel()
    deadline = time.monotonic() + timeout_s
    peer_addr: tuple[str, int] | None = None
    cookie: str | None = None  # source-address proof (challenge echo)

    def _request() -> bool:
        body = {"key": target_key_hex}
        if cookie is not None:
            body["cookie"] = cookie
        return raw.send(rendezvous[0], rendezvous[1],
                        _msg("request", **body))

    if not _request():
        raise ConnectionError(f"cannot send to rendezvous {rendezvous}")
    last_req = time.monotonic()
    burst_task: asyncio.Task | None = None
    try:
        while time.monotonic() < deadline:
            got = await raw.recv(timeout_s=0.5)
            now = time.monotonic()
            if got is None:
                if peer_addr is None and now - last_req > 1.0:
                    _request()
                    last_req = now
                continue
            payload, host, port = got
            msg = _parse(payload)
            if msg is None:
                continue
            op = msg.get("op")
            if op == "challenge" and (host, port) == rendezvous:
                # Echo the cookie straight back: receiving it at our
                # claimed source IS the proof the rendezvous wants.
                cookie = str(msg.get("cookie", "")) or None
                _request()
                last_req = now
                continue
            if op == "unknown" and (host, port) == rendezvous:
                raise ConnectionError(
                    f"rendezvous does not know provider {target_key_hex[:12]}")
            if op == "busy" and (host, port) == rendezvous:
                raise ConnectionError(
                    "rendezvous rate-limited this source (invite budget); "
                    "back off before re-dialing")
            if op == "peer" and (host, port) == rendezvous:
                addr = msg.get("addr") or []
                if len(addr) == 2 and peer_addr is None:
                    peer_addr = (str(addr[0]), int(addr[1]))

                    async def _burst() -> None:
                        for _ in range(PUNCH_BURST):
                            raw.send(peer_addr[0], peer_addr[1],
                                     _msg("punch", key="client"))
                            await asyncio.sleep(PUNCH_INTERVAL_S)

                    burst_task = asyncio.get_running_loop().create_task(
                        _burst())
            elif op == "punch" and peer_addr is not None and (
                    host, port) == peer_addr:
                # provider's punch arrived: the path works both ways
                logger.debug(f"punch confirmed from {host}:{port}")
                return f"udp://{peer_addr[0]}:{peer_addr[1]}"
        if peer_addr is not None:
            # No punch seen (e.g. provider's confirm was lost) — the
            # pinholes may still be open; let the dial try.
            return f"udp://{peer_addr[0]}:{peer_addr[1]}"
        raise ConnectionError(
            f"no rendezvous answer for {target_key_hex[:12]} "
            f"within {timeout_s}s")
    finally:
        if burst_task is not None and not burst_task.done():
            burst_task.cancel()
