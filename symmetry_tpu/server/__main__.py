import asyncio

from symmetry_tpu.server.broker import main

if __name__ == "__main__":
    asyncio.run(main())
