"""Peer/session registry: the server-side data model.

The reference repo ships only the provider, but its `src/types.ts:182-208`
preserves the server's SQLite schema as TypeScript types — `PeerUpsert`
(peer_key/discovery_key/config/model aliases), `Session`
(id/provider_id/created_at), `PeerWithSession` — and `sqlite3` remains a
declared dependency (package.json:17-19). This module implements that data
model: a sqlite-backed store of providers and sessions.

Load balancing rule ("The Tower ensures no single Provider bears too heavy a
burden", reference readme.md Architecture): selection = model match, online,
below max_connections, least-loaded first.
"""

from __future__ import annotations

import json
import sqlite3
import time
from dataclasses import dataclass
from typing import Any

from symmetry_tpu.utils.metrics import METRICS, MetricName

_SCHEMA = """
CREATE TABLE IF NOT EXISTS peers (
    peer_key        TEXT PRIMARY KEY,   -- hex Ed25519 public key
    discovery_key   TEXT NOT NULL,
    name            TEXT,
    model_name      TEXT NOT NULL,
    address         TEXT,               -- dialable address (tcp://host:port)
    public          INTEGER NOT NULL DEFAULT 1,
    online          INTEGER NOT NULL DEFAULT 1,
    connections     INTEGER NOT NULL DEFAULT 0,
    max_connections INTEGER NOT NULL DEFAULT 10,
    queued          INTEGER NOT NULL DEFAULT 0,  -- reported engine backlog
    queued_at       REAL NOT NULL DEFAULT 0,     -- when `queued` was reported

    data_collection INTEGER NOT NULL DEFAULT 0,
    config          TEXT,               -- sanitized config JSON (no secrets)
    metrics         TEXT,               -- latest load/latency report JSON
    joined_at       REAL NOT NULL,
    last_seen       REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_peers_model ON peers (model_name, online);
CREATE TABLE IF NOT EXISTS sessions (
    id          TEXT PRIMARY KEY,
    peer_key    TEXT NOT NULL,          -- provider
    client_key  TEXT,                   -- requesting client (hex)
    model_name  TEXT NOT NULL,
    created_at  REAL NOT NULL,
    expires_at  REAL NOT NULL,
    completed   INTEGER NOT NULL DEFAULT 0,
    tokens      INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS completions (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    session_id  TEXT,
    peer_key    TEXT NOT NULL,
    tokens      INTEGER NOT NULL DEFAULT 0,
    reported_at REAL NOT NULL
);
"""


@dataclass(slots=True)
class ProviderRow:
    peer_key: str
    discovery_key: str
    name: str | None
    model_name: str
    address: str | None
    public: bool
    online: bool
    connections: int
    max_connections: int
    data_collection: bool
    config: dict[str, Any] | None
    metrics: dict[str, Any] | None      # latest METRICS report (tok/s, TTFT)
    joined_at: float
    last_seen: float


def _row_to_provider(row: sqlite3.Row) -> ProviderRow:
    return ProviderRow(
        peer_key=row["peer_key"],
        discovery_key=row["discovery_key"],
        name=row["name"],
        model_name=row["model_name"],
        address=row["address"],
        public=bool(row["public"]),
        online=bool(row["online"]),
        connections=row["connections"],
        max_connections=row["max_connections"],
        data_collection=bool(row["data_collection"]),
        config=json.loads(row["config"]) if row["config"] else None,
        metrics=json.loads(row["metrics"]) if row["metrics"] else None,
        joined_at=row["joined_at"],
        last_seen=row["last_seen"],
    )


class Registry:
    """sqlite peer/session store. ':memory:' for tests, file path for prod."""

    # A reported `queued` backlog steers selection only while fresh: shed-
    # triggered METRICS pushes stop once the backlog drains, so a stale
    # reading would keep deprioritizing a now-idle provider. Two provider
    # health-report intervals (provider.HEALTH_INTERVAL_S = 15 s) without
    # a fresh report → the backlog is treated as 0.
    QUEUED_STALE_S = 30.0

    def __init__(self, db_path: str = ":memory:") -> None:
        self._db = sqlite3.connect(db_path)
        self._db.row_factory = sqlite3.Row
        self._db.executescript(_SCHEMA)
        self._migrate()
        # Restart recovery: anything marked online in a previous run is stale.
        self._db.execute(
            "UPDATE peers SET online = 0, connections = 0, queued = 0,"
            " queued_at = 0")
        self._db.commit()
        # Server-side fleet telemetry (utils/metrics.py): the router's
        # steering inputs as always-on series — online count and each
        # provider's reported engine backlog (the `queued` column the
        # steering ORDER BY reads).
        self._m_online = METRICS.gauge(
            MetricName.SERVER_PROVIDERS_ONLINE,
            "providers currently online")
        self._m_queued = METRICS.gauge(
            MetricName.SERVER_PROVIDER_QUEUED,
            "per-provider reported engine backlog",
            labels=("provider", "model"))

    def _gauge_online(self) -> None:
        row = self._db.execute(
            "SELECT COUNT(*) AS n FROM peers WHERE online = 1").fetchone()
        self._m_online.set(int(row["n"]))

    def _migrate(self) -> None:
        """Columns added after a release: CREATE TABLE IF NOT EXISTS is a
        no-op on a pre-existing file DB, so bring it up to schema here."""
        have = {row["name"] for row in
                self._db.execute("PRAGMA table_info(peers)")}
        if "metrics" not in have:
            self._db.execute("ALTER TABLE peers ADD COLUMN metrics TEXT")
        if "queued" not in have:
            self._db.execute(
                "ALTER TABLE peers ADD COLUMN queued INTEGER NOT NULL "
                "DEFAULT 0")
        if "queued_at" not in have:
            self._db.execute(
                "ALTER TABLE peers ADD COLUMN queued_at REAL NOT NULL "
                "DEFAULT 0")
        self._db.commit()

    # --- providers (PeerUpsert semantics, reference src/types.ts:203-208) ---

    def upsert_provider(self, *, peer_key: str, discovery_key: str,
                        model_name: str, name: str | None = None,
                        address: str | None = None, public: bool = True,
                        max_connections: int = 10, data_collection: bool = False,
                        config: dict[str, Any] | None = None) -> None:
        now = time.time()
        self._db.execute(
            """INSERT INTO peers (peer_key, discovery_key, name, model_name, address,
                                  public, online, connections, max_connections,
                                  data_collection, config, joined_at, last_seen)
               VALUES (?,?,?,?,?,?,1,0,?,?,?,?,?)
               ON CONFLICT(peer_key) DO UPDATE SET
                   discovery_key=excluded.discovery_key, name=excluded.name,
                   model_name=excluded.model_name, address=excluded.address,
                   public=excluded.public, online=1, connections=0,
                   max_connections=excluded.max_connections,
                   data_collection=excluded.data_collection,
                   config=excluded.config, last_seen=excluded.last_seen""",
            (peer_key, discovery_key, name, model_name, address, int(public),
             max_connections, int(data_collection),
             json.dumps(config) if config else None, now, now),
        )
        self._db.commit()
        self._gauge_online()

    def set_offline(self, peer_key: str) -> None:
        row = self.get_provider(peer_key)
        self._db.execute(
            "UPDATE peers SET online = 0, connections = 0 WHERE peer_key = ?",
            (peer_key,),
        )
        self._db.commit()
        self._gauge_online()
        # Drop the departed provider's backlog series: a labeled gauge
        # otherwise exports its last value forever, and churn of
        # ephemeral providers would grow series without bound.
        if row is not None:
            self._m_queued.remove(provider=peer_key[:12],
                                  model=row.model_name)

    def touch(self, peer_key: str) -> None:
        self._db.execute(
            "UPDATE peers SET last_seen = ? WHERE peer_key = ?",
            (time.time(), peer_key),
        )
        self._db.commit()

    def set_metrics(self, peer_key: str, metrics: dict[str, Any]) -> None:
        """Latest provider load/latency report (`metrics` key): tok/s,
        in-flight, TTFT percentiles — the server-side view of provider
        health beyond liveness. The reported engine backlog (`queued`) is
        lifted into its own column so select_provider can steer away from
        overloaded providers without parsing JSON per candidate."""
        queued = metrics.get("queued")
        # bool is an int subclass: True would silently steer as backlog 1.
        if (not isinstance(queued, int) or isinstance(queued, bool)
                or queued < 0):
            queued = 0
        now = time.time()
        self._db.execute(
            "UPDATE peers SET metrics = ?, queued = ?, queued_at = ?,"
            " last_seen = ? WHERE peer_key = ?",
            (json.dumps(metrics), queued, now, now, peer_key),
        )
        self._db.commit()
        # Gauge only for a LIVE provider: a straggler METRICS heartbeat
        # processed after set_offline must not resurrect the series the
        # offline path just removed (it would then export its last
        # value forever — the churn leak the removal exists to stop).
        row = self.get_provider(peer_key)
        if row is not None and row.online:
            self._m_queued.set(queued, provider=peer_key[:12],
                               model=row.model_name)

    def set_connections(self, peer_key: str, count: int) -> None:
        """`conectionSize` reports (reference key, src/constants.ts:5)."""
        self._db.execute(
            "UPDATE peers SET connections = ?, last_seen = ? WHERE peer_key = ?",
            (count, time.time(), peer_key),
        )
        self._db.commit()

    def get_provider(self, peer_key: str) -> ProviderRow | None:
        row = self._db.execute(
            "SELECT * FROM peers WHERE peer_key = ?", (peer_key,)
        ).fetchone()
        return _row_to_provider(row) if row else None

    def select_provider(self, model_name: str | None = None,
                        exclude: tuple[str, ...] = ()) -> ProviderRow | None:
        """Model-matched, online, capacity-available, least-loaded provider.

        `exclude` drops specific peer keys — clients re-requesting after a
        provider died mid-stream must not be handed the same one back."""
        query = (
            "SELECT * FROM peers WHERE online = 1 AND public = 1"
            " AND connections < max_connections"
        )
        params: list = []
        if model_name:
            query += " AND model_name = ?"
            params.append(model_name)
        if exclude:
            query += (" AND peer_key NOT IN ("
                      + ",".join("?" * len(exclude)) + ")")
            params.extend(exclude)
        # Steering: reported engine backlog first (a provider shedding
        # load must stop receiving assignments while an idle one exists),
        # then the reference's least-loaded-by-connections order. A
        # backlog report older than QUEUED_STALE_S is decayed to 0 — the
        # provider stopped pushing METRICS because it stopped shedding.
        query += (" ORDER BY (CASE WHEN queued_at >= ? THEN queued"
                  " ELSE 0 END) ASC,"
                  " CAST(connections AS REAL) / max_connections ASC,"
                  " last_seen DESC LIMIT 1")
        params.append(time.time() - self.QUEUED_STALE_S)
        row = self._db.execute(query, tuple(params)).fetchone()
        return _row_to_provider(row) if row else None

    def list_providers(self, online_only: bool = True) -> list[ProviderRow]:
        q = "SELECT * FROM peers"
        if online_only:
            q += " WHERE online = 1"
        return [_row_to_provider(r) for r in self._db.execute(q)]

    def list_models(self) -> list[dict[str, Any]]:
        rows = self._db.execute(
            """SELECT model_name, COUNT(*) AS providers,
                      SUM(max_connections - connections) AS free_slots
               FROM peers WHERE online = 1 AND public = 1 GROUP BY model_name"""
        )
        return [dict(r) for r in rows]

    def stale_providers(self, older_than_s: float) -> list[str]:
        cutoff = time.time() - older_than_s
        rows = self._db.execute(
            "SELECT peer_key FROM peers WHERE online = 1 AND last_seen < ?",
            (cutoff,),
        )
        return [r["peer_key"] for r in rows]

    # --- sessions (reference src/types.ts:182-201) ---

    def create_session(self, *, session_id: str, peer_key: str,
                       client_key: str | None, model_name: str,
                       ttl_s: float = 3600.0) -> None:
        now = time.time()
        self._db.execute(
            """INSERT INTO sessions (id, peer_key, client_key, model_name,
                                     created_at, expires_at) VALUES (?,?,?,?,?,?)""",
            (session_id, peer_key, client_key, model_name, now, now + ttl_s),
        )
        self._db.commit()

    def invalidate_sessions_for(self, peer_key: str) -> int:
        """Expire every incomplete session assigned to a dead provider so
        verifySession reports them invalid and clients re-request
        (SURVEY §5.3: request requeue on provider loss). Returns the count
        invalidated."""
        cur = self._db.execute(
            "UPDATE sessions SET expires_at = 0"
            " WHERE peer_key = ? AND completed = 0 AND expires_at > ?",
            (peer_key, time.time()),
        )
        self._db.commit()
        return cur.rowcount

    def session_valid(self, session_id: str) -> bool:
        row = self._db.execute(
            "SELECT expires_at FROM sessions WHERE id = ?", (session_id,)
        ).fetchone()
        return bool(row and row["expires_at"] > time.time())

    def report_completion(self, *, peer_key: str, session_id: str | None,
                          tokens: int) -> None:
        self._db.execute(
            "INSERT INTO completions (session_id, peer_key, tokens, reported_at)"
            " VALUES (?,?,?,?)",
            (session_id, peer_key, tokens, time.time()),
        )
        if session_id:
            self._db.execute(
                "UPDATE sessions SET completed = 1, tokens = tokens + ? WHERE id = ?",
                (tokens, session_id),
            )
        self._db.commit()

    def close(self) -> None:
        self._db.close()
