from symmetry_tpu.server.registry import Registry

__all__ = ["Registry", "SymmetryServer"]


def __getattr__(name: str):
    # Lazy (PEP 562): the broker pulls the identity/crypto stack; the
    # registry (sqlite data model) must stay importable without it.
    if name == "SymmetryServer":
        from symmetry_tpu.server.broker import SymmetryServer

        return SymmetryServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
