"""The Symmetry server: session broker, model router, load balancer.

The reference repo ships only the provider; the server it registers with lives
in an absent sibling repo (SURVEY §0.1). Its observable protocol — the
`serverMessageKeys` vocabulary (reference src/constants.ts:3-20), the provider
join flow (src/provider.ts:83-131), and the SQLite data model
(src/types.ts:182-208) — is re-created here:

  provider → join {config, discoveryKey, address}  → joinAck {key: serverKey}
  provider → challenge {challenge}                 → challengeResponse {signature}
  provider → connectionSize n                      → (load update)
  provider → reportCompletion {tokens, sessionId}  → (usage record)
  provider → leave                                 → (deregistered; graceful)
  server   → ping (periodic)                       ← pong (liveness)
  client   → requestProvider {modelName}           → providerDetails {provider, sessionToken}
  client   → verifySession {sessionId}             → sessionValid {valid}
  client   → providerList                          → providerList {models}

Authentication is two-layer: the Noise handshake proves key ownership at
connect time (enforced, unlike the reference's advisory check), and the
challenge/response flow is kept for wire-level parity.
"""

from __future__ import annotations

import asyncio
import os
import uuid
from typing import Any

from symmetry_tpu.identity import Identity
from symmetry_tpu.network.peer import Peer
from symmetry_tpu.protocol.keys import MessageKey
from symmetry_tpu.server import tokens
from symmetry_tpu.server.registry import Registry
from symmetry_tpu.transport.base import Connection, Listener, Transport
from symmetry_tpu.utils.logging import logger

PING_INTERVAL_S = 30.0
STALE_AFTER_S = 90.0


class SymmetryServer:
    def __init__(
        self,
        identity: Identity,
        transport: Transport,
        *,
        db_path: str = ":memory:",
        ping_interval_s: float = PING_INTERVAL_S,
        stale_after_s: float = STALE_AFTER_S,
        punch_port: int | None = None,
    ) -> None:
        self.identity = identity
        self._transport = transport
        self.registry = Registry(db_path)
        self._ping_interval = ping_interval_s
        self._stale_after = stale_after_s
        self._listener: Listener | None = None
        # NAT rendezvous (network/natpunch.py): providers register their
        # reflexive UDP address here; clients punch through it. None
        # disables; 0 binds an ephemeral port (tests).
        self._punch_port = punch_port
        self._punch: Any = None
        self._provider_peers: dict[str, Peer] = {}  # peer_key hex → live peer
        # relay splices (NAT fallback, network/relay.py): relayId →
        # {"a": client peer, "b": provider peer | None (pre-accept)}
        self._relays: dict[str, dict[str, Any]] = {}  # a/b Peer + client_key
        self._tasks: set[asyncio.Task] = set()
        self._stopped = asyncio.Event()

    @property
    def address(self) -> str:
        assert self._listener is not None, "server not started"
        return self._listener.address

    async def start(self, address: str) -> None:
        self._listener = await self._transport.listen(address, self._on_connection)
        self._spawn(self._liveness_loop())
        if self._punch_port is not None:
            from symmetry_tpu.network.natpunch import PunchRendezvous

            # Best-effort: a taken UDP port must cost NAT traversal, not
            # the whole server (a second server on the same host would
            # otherwise fail startup on the default punch port).
            try:
                self._punch = PunchRendezvous()
                await self._punch.start(port=self._punch_port)
                logger.info(
                    f"punch rendezvous on udp port {self._punch.port}")
            except OSError as exc:
                self._punch = None
                logger.warning(f"punch rendezvous disabled "
                               f"(udp port {self._punch_port}): {exc}")
        logger.info(
            f"symmetry server listening on {self.address} "
            f"key={self.identity.public_hex}"
        )

    @property
    def punch_port(self) -> int | None:
        return self._punch.port if self._punch is not None else None

    async def stop(self) -> None:
        self._stopped.set()
        if self._punch is not None:
            await self._punch.stop()
            self._punch = None
        for task in list(self._tasks):
            task.cancel()
        for peer in list(self._provider_peers.values()):
            await peer.close()
        if self._listener is not None:
            await self._listener.close()
        self.registry.close()

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    # --- connection handling ---

    async def _on_connection(self, conn: Connection) -> None:
        peer = await Peer.connect(conn, self.identity, initiator=False)
        peer_key = peer.remote_public_hex
        logger.debug(f"server: peer {peer_key[:12]} connected")
        try:
            async for msg in peer:
                await self._dispatch(peer, peer_key, msg.key, msg.data)
        finally:
            # A dropped connection is an implicit leave (the reference server
            # detects departure via ping timeout; we do it immediately too).
            if self._provider_peers.get(peer_key) is peer:
                del self._provider_peers[peer_key]
                self._provider_down(peer_key, "disconnected")
            for relay_id, relay in list(self._relays.items()):
                if relay["a"] is peer or relay["b"] is peer:
                    await self._teardown_relay(relay_id, peer)

    async def _dispatch(self, peer: Peer, peer_key: str, key: str, data: Any) -> None:
        if key == MessageKey.CHALLENGE:
            # Reference flow (src/provider.ts:95-101,143-171): peer sends random
            # bytes, server returns its signature over them.
            challenge_hex = (data or {}).get("challenge", "")
            try:
                challenge = bytes.fromhex(challenge_hex)
            except ValueError:
                challenge = b""
            if not 8 <= len(challenge) <= 64:
                await peer.send(MessageKey.INFERENCE_ERROR, {"error": "bad challenge"})
                return
            sig = self.identity.sign(challenge)
            await peer.send(
                MessageKey.CHALLENGE_RESPONSE,
                {"signature": sig.hex(), "serverKey": self.identity.public_hex},
            )
        elif key == MessageKey.JOIN:
            await self._handle_join(peer, peer_key, data or {})
        elif key in (MessageKey.CONNECTION_SIZE,):
            count = data if isinstance(data, int) else (data or {}).get("connections", 0)
            self.registry.set_connections(peer_key, int(count))
        elif key in (MessageKey.PONG, MessageKey.HEARTBEAT):
            self.registry.touch(peer_key)
        elif key == MessageKey.METRICS:
            if isinstance(data, dict):
                self.registry.set_metrics(peer_key, data)
            else:
                self.registry.touch(peer_key)
        elif key == MessageKey.REPORT_COMPLETION:
            d = data or {}
            self.registry.report_completion(
                peer_key=peer_key,
                session_id=d.get("sessionId"),
                tokens=int(d.get("tokens", 0)),
            )
        elif key == MessageKey.LEAVE:
            self._provider_peers.pop(peer_key, None)
            self._provider_down(peer_key, "left gracefully")
        elif key == MessageKey.REQUEST_PROVIDER:
            await self._handle_request_provider(peer, peer_key, data or {})
        elif key == MessageKey.VERIFY_SESSION:
            session_id = (data or {}).get("sessionId", "")
            await peer.send(
                MessageKey.SESSION_VALID,
                {"sessionId": session_id, "valid": self.registry.session_valid(session_id)},
            )
        elif key == MessageKey.PROVIDER_LIST:
            await peer.send(MessageKey.PROVIDER_LIST, {"models": self.registry.list_models()})
        elif key == MessageKey.PING:
            await peer.send(MessageKey.PONG)
        elif key == MessageKey.RELAY_CONNECT:
            await self._handle_relay_connect(peer, peer_key, data or {})
        elif key == MessageKey.RELAY_ACCEPT:
            await self._handle_relay_accept(peer, data or {})
        elif key == MessageKey.RELAY_DATA:
            await self._handle_relay_data(peer, data or {})
        elif key == MessageKey.RELAY_CLOSE:
            await self._teardown_relay(str((data or {}).get("id", "")), peer)
        else:
            logger.debug(f"server: unhandled key {key!r} from {peer_key[:12]}")

    def _provider_down(self, peer_key: str, reason: str) -> None:
        """One path for every way a provider dies: deregister AND expire
        its in-flight sessions, so clients whose stream broke re-request a
        provider instead of retrying a dead assignment (round-2 verdict:
        sessions of a dead provider just died with it)."""
        self.registry.set_offline(peer_key)
        n = self.registry.invalidate_sessions_for(peer_key)
        logger.info(f"provider {peer_key[:12]} {reason}"
                    + (f"; invalidated {n} session(s)" if n else ""))

    async def _handle_join(self, peer: Peer, peer_key: str, data: dict) -> None:
        config = data.get("config") or {}
        model_name = config.get("modelName") or data.get("modelName")
        if not model_name:
            await peer.send(MessageKey.INFERENCE_ERROR, {"error": "join missing modelName"})
            return
        self.registry.upsert_provider(
            peer_key=peer_key,
            discovery_key=data.get("discoveryKey", peer.remote_discovery_key.hex()),
            model_name=model_name,
            name=config.get("name"),
            address=data.get("address"),
            public=bool(config.get("public", True)),
            max_connections=int(config.get("maxConnections", 10)),
            data_collection=bool(config.get("dataCollectionEnabled", False)),
            config=config,
        )
        self._provider_peers[peer_key] = peer
        await peer.send(MessageKey.JOIN_ACK, {"serverKey": self.identity.public_hex})
        logger.info(f"provider {peer_key[:12]} joined serving {model_name!r}")

    async def _handle_request_provider(self, peer: Peer, client_key: str, data: dict) -> None:
        model_name = data.get("modelName")
        exclude = tuple(str(k) for k in (data.get("excludePeers") or ())
                        if isinstance(k, str))[:16]
        row = self.registry.select_provider(model_name, exclude=exclude)
        if row is None:
            await peer.send(
                MessageKey.PROVIDER_DETAILS,
                {"error": f"no provider available for model {model_name!r}"},
            )
            return
        session_id = str(uuid.uuid4())
        self.registry.create_session(
            session_id=session_id,
            peer_key=row.peer_key,
            client_key=client_key,
            model_name=row.model_name,
        )
        token = tokens.mint(
            self.identity,
            session_id=session_id,
            client_key=client_key,
            model_name=row.model_name,
        )
        await peer.send(
            MessageKey.PROVIDER_DETAILS,
            {
                "sessionId": session_id,
                "sessionToken": token,
                "provider": {
                    "peerKey": row.peer_key,
                    "discoveryKey": row.discovery_key,
                    "address": row.address,
                    "modelName": row.model_name,
                    "name": row.name,
                    "dataCollectionEnabled": row.data_collection,
                },
            },
        )

    # --- relay splice (NAT fallback; network/relay.py protocol notes) ---

    # Pending+active relay splices one client peer key may hold at once.
    # Without a cap a single authenticated client looping relayConnect
    # grows _relays unboundedly AND makes the provider dial+spawn a
    # _serve_relay task per RELAY_OPEN before its own maxConnections cap
    # applies (round-3 advisor) — the same per-peer discipline as the
    # provider's inference cap.
    MAX_RELAYS_PER_CLIENT = 4
    # A splice the provider never accepts (dial-back failed) must expire,
    # or 4 such attempts would permanently lock the client out of the
    # relay fallback for the life of its connection.
    PENDING_RELAY_TTL_S = 30.0

    async def _handle_relay_connect(self, peer: Peer, client_key: str,
                                    data: dict) -> None:
        provider_key = str(data.get("providerKey", ""))
        control = self._provider_peers.get(provider_key)
        if control is None or control.closed:
            await peer.send(MessageKey.INFERENCE_ERROR,
                            {"error": f"provider {provider_key[:12]} not "
                                      f"connected; cannot relay"})
            return
        import time as _time

        now = _time.monotonic()
        for rid, r in list(self._relays.items()):
            if (r["b"] is None
                    and now - r.get("opened_at", now)
                    > self.PENDING_RELAY_TTL_S):
                await self._teardown_relay(rid, peer)
        held = sum(1 for r in self._relays.values()
                   if r.get("client_key") == client_key)
        if held >= self.MAX_RELAYS_PER_CLIENT:
            await peer.send(MessageKey.RELAY_CLOSE,
                            {"id": "", "error": "relay cap reached"})
            return
        relay_id = str(uuid.uuid4())
        self._relays[relay_id] = {"a": peer, "b": None,
                                  "client_key": client_key,
                                  "opened_at": now}
        try:
            await control.send(MessageKey.RELAY_OPEN, {"id": relay_id})
        except (ConnectionError, OSError):
            del self._relays[relay_id]
            await peer.send(MessageKey.INFERENCE_ERROR,
                            {"error": "provider control channel failed"})
            return
        logger.debug(f"relay {relay_id[:8]} pending: {client_key[:12]} → "
                     f"{provider_key[:12]}")

    async def _handle_relay_accept(self, peer: Peer, data: dict) -> None:
        relay_id = str(data.get("id", ""))
        relay = self._relays.get(relay_id)
        if relay is None or relay["b"] is not None:
            await peer.send(MessageKey.RELAY_CLOSE, {"id": relay_id})
            return
        relay["b"] = peer
        for end in (relay["a"], relay["b"]):
            await end.send(MessageKey.RELAY_READY, {"id": relay_id})
        logger.debug(f"relay {relay_id[:8]} spliced")

    async def _handle_relay_data(self, peer: Peer, data: dict) -> None:
        relay_id = str(data.get("id", ""))
        relay = self._relays.get(relay_id)
        if relay is None:
            return
        if peer is relay["a"]:
            other = relay["b"]
        elif peer is relay["b"]:
            other = relay["a"]
        else:
            return  # third parties cannot inject into a splice
        if other is None or other.closed:
            await self._teardown_relay(relay_id, peer)
            return
        try:
            # Forward verbatim — the frame is client↔provider Noise
            # ciphertext this server cannot read.
            await other.send(MessageKey.RELAY_DATA, data)
        except (ConnectionError, OSError):
            await self._teardown_relay(relay_id, peer)

    async def _teardown_relay(self, relay_id: str, requester: Peer) -> None:
        relay = self._relays.pop(relay_id, None)
        if relay is None:
            return
        for end in (relay["a"], relay["b"]):
            if end is not None and end is not requester and not end.closed:
                try:
                    await end.send(MessageKey.RELAY_CLOSE, {"id": relay_id})
                except (ConnectionError, OSError):
                    pass

    # --- liveness (reference: server→provider ping, src/provider.ts:124-126) ---

    async def _liveness_loop(self) -> None:
        while not self._stopped.is_set():
            await asyncio.sleep(self._ping_interval)
            for peer_key, peer in list(self._provider_peers.items()):
                if peer.closed:
                    continue
                try:
                    await peer.send(MessageKey.PING)
                except (ConnectionError, OSError):
                    self._provider_peers.pop(peer_key, None)
                    self._provider_down(peer_key, "ping failed")
            for peer_key in self.registry.stale_providers(self._stale_after):
                self._provider_peers.pop(peer_key, None)
                self._provider_down(peer_key, "stale")


async def main() -> None:
    """CLI entry: python -m symmetry_tpu.server [--port N] [--db PATH]"""
    import argparse

    parser = argparse.ArgumentParser(description="Symmetry routing server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=4848)
    parser.add_argument("--scheme", default="tcp", choices=("tcp", "udp"),
                        help="udp engages the native udpstream transport")
    parser.add_argument("--db", default=os.path.expanduser("~/.config/symmetry/server.db"))
    parser.add_argument("--seed-name", default=None,
                        help="derive a stable identity from this name")
    parser.add_argument("--punch-port", type=int, default=4849,
                        help="UDP port for the NAT-punch rendezvous "
                             "(-1 disables)")
    args = parser.parse_args()

    from symmetry_tpu.transport import transport_for

    identity = (
        Identity.from_name(args.seed_name) if args.seed_name else Identity.generate()
    )
    if args.db != ":memory:":
        os.makedirs(os.path.dirname(args.db), exist_ok=True)
    address = f"{args.scheme}://{args.host}:{args.port}"
    server = SymmetryServer(
        identity, transport_for(address), db_path=args.db,
        punch_port=None if args.punch_port < 0 else args.punch_port)
    await server.start(address)
    print(f"serverKey: {identity.public_hex}", flush=True)
    try:
        await asyncio.Event().wait()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await server.stop()


if __name__ == "__main__":
    asyncio.run(main())
