"""symlint: project-invariant static analysis (see tools/symlint.py).

Eight checkers over the repo, each making one runtime invariant
statically checkable. Six are flat AST passes:

  wire-contract     host-pipe op / MessageKey producer↔consumer sets
  concurrency       cross-thread mutation locks; blocking-in-async
  recompile-hazard  value syncs / data branches inside jit traces
  fault-seam        SYMMETRY_FAULTS arming ↔ FAULTS.point guards
  metric-names      MetricName registry ↔ METRICS emission sites
  knobs             tpu.* knobs: TpuConfig ↔ README ↔ read sites

and two are path-sensitive, built on the CFG + abstract-state walker
in dataflow.py (one node per statement, exception/finally/early-return
edges, per-path states — the PR-12 class of bug lives only on paths):

  lifecycle         paired resources (radix pins, insert plans, pool
                    blocks, bare locks) released on EVERY path out,
                    exception edges included; double-release;
                    use-after-release
  donation          jax.jit donate_argnums buffers never read after
                    the jitted call without rebinding

Run via `python tools/symlint.py` (text, --json, or --sarif output,
--baseline suppression file, exit 1 on non-baselined findings). The
suite is also importable — `run(root)` — which is how
tests/test_analysis.py asserts the repo itself stays clean.
"""

from __future__ import annotations

from symmetry_tpu.analysis import (
    concurrency,
    donation,
    fault_seams,
    knobs,
    lifecycle,
    metric_names,
    recompile,
    wire_contract,
)
from symmetry_tpu.analysis.core import (
    Baseline,
    CheckerSpec,
    Finding,
    Project,
    run_suite,
)

ALL_CHECKERS: tuple[CheckerSpec, ...] = (
    wire_contract.SPEC,
    concurrency.SPEC,
    recompile.SPEC,
    fault_seams.SPEC,
    metric_names.SPEC,
    lifecycle.SPEC,
    donation.SPEC,
    knobs.SPEC,
)


def run(root: str, checkers: tuple[CheckerSpec, ...] = ALL_CHECKERS,
        baseline: Baseline | None = None,
        rels: list[str] | None = None) -> list[Finding]:
    """Scan `root` (or just `rels` under it) with the given checkers."""
    project = Project.scan(root, rels)
    return run_suite(project, checkers, baseline)


__all__ = ["ALL_CHECKERS", "Baseline", "CheckerSpec", "Finding",
           "Project", "run", "run_suite"]
