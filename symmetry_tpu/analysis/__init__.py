"""symlint: project-invariant static analysis (see tools/symlint.py).

Five AST checkers over the repo, each making one runtime invariant
statically checkable:

  wire-contract     host-pipe op / MessageKey producer↔consumer sets
  concurrency       cross-thread mutation locks; blocking-in-async
  recompile-hazard  value syncs / data branches inside jit traces
  fault-seam        SYMMETRY_FAULTS arming ↔ FAULTS.point guards
  metric-names      MetricName registry ↔ METRICS emission sites

Run via `python tools/symlint.py` (text or --json, --baseline
suppression file, exit 1 on non-baselined findings). The suite is also
importable — `run(root)` — which is how tests/test_analysis.py asserts
the repo itself stays clean.
"""

from __future__ import annotations

from symmetry_tpu.analysis import (
    concurrency,
    fault_seams,
    metric_names,
    recompile,
    wire_contract,
)
from symmetry_tpu.analysis.core import (
    Baseline,
    CheckerSpec,
    Finding,
    Project,
    run_suite,
)

ALL_CHECKERS: tuple[CheckerSpec, ...] = (
    wire_contract.SPEC,
    concurrency.SPEC,
    recompile.SPEC,
    fault_seams.SPEC,
    metric_names.SPEC,
)


def run(root: str, checkers: tuple[CheckerSpec, ...] = ALL_CHECKERS,
        baseline: Baseline | None = None,
        rels: list[str] | None = None) -> list[Finding]:
    """Scan `root` (or just `rels` under it) with the given checkers."""
    project = Project.scan(root, rels)
    return run_suite(project, checkers, baseline)


__all__ = ["ALL_CHECKERS", "Baseline", "CheckerSpec", "Finding",
           "Project", "run", "run_suite"]
