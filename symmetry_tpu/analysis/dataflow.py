"""Intraprocedural CFG + path-sensitive forward dataflow engine.

PR 12's review found a crash none of the flat AST checkers could see:
`plan_insert` triggered an eviction that freed the blocks its own
matched prefix was about to extend — a *path*-sensitive bug in the
acquire/pin/free discipline the paged KV pool makes load-bearing. The
flat checkers pattern-match single statements; this module gives the
suite the machinery to reason about *orderings*: which statements can
execute before which, on which paths, including the paths only an
exception takes.

Two pieces:

  - `build_cfg(func)`: a control-flow graph over one function's AST.
    One node per executed statement part (an `if`'s node is its test,
    a `for`'s its iterator), edges labeled normal / true-branch /
    false-branch / exception. `try/except/finally` is modeled
    faithfully: every statement that can raise gets an edge to the
    innermost handler set (and past it, when no handler is a
    catch-all), and `finally` bodies are CLONED per continuation kind
    (fallthrough, exception, return, break, continue) so the analysis
    sees the release-in-finally that makes a leaky-looking path safe.
    `return`/`raise`/`break`/`continue` edges leave their block early;
    the function has one normal exit and one exceptional exit.

  - `analyze(func, semantics)`: a forward walker that pushes abstract
    states through the CFG to a bounded fixpoint. States are opaque
    hashable values owned by the checker's `semantics` object; the
    engine only joins them as SETS (per-path states are kept distinct
    until they converge — that is the path-sensitivity), prunes
    branches the semantics declares infeasible (`if x is None` on a
    state that knows x is held), and reports exit states.

Checkers plug in via the `Semantics` duck type:

    initial() -> state
    transfer(node, state) -> (post_state, exc_state, findings)
        `exc_state` is what propagates along this node's exception
        edge — usually the PRE state (the statement's effects may not
        have happened), but release-like effects should stick (a
        release that raises still released).
    on_branch(test, state, taken: bool) -> state | None
        None = branch infeasible under this state (pruned).
    at_exit(state, exceptional: bool) -> findings

Findings are checker-defined hashables (dedup'd across paths by the
engine). The walker is bounded (`max_states_per_node`, `max_steps`)
so pathological functions degrade to partial coverage, never hangs —
the CI gate's whole value is running in seconds.

Pure stdlib, no JAX import (the CI gate runs before `pip install`).
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Any, Iterable, Iterator

from symmetry_tpu.analysis.core import dotted_name

EDGE_NORMAL = "n"
EDGE_TRUE = "t"
EDGE_FALSE = "f"
EDGE_EXC = "e"

# Exceptions a handler with one of these names catches "everything"
# for our purposes: no propagate-past-handlers edge is added.
_CATCH_ALL_NAMES = {"Exception", "BaseException"}


class Node:
    """One CFG node. `stmt` is the governing AST statement (None for
    synthetic join/entry/exit nodes), `expr` the fragment actually
    evaluated AT this node (an `if` node evaluates only its test),
    `test` the branch condition when outgoing t/f edges exist."""

    __slots__ = ("stmt", "expr", "test", "label", "succs")

    def __init__(self, stmt: ast.AST | None = None,
                 expr: ast.AST | None = None,
                 test: ast.AST | None = None, label: str = "") -> None:
        self.stmt = stmt
        self.expr = expr
        self.test = test
        self.label = label
        self.succs: list[tuple["Node", str]] = []

    def edge(self, other: "Node", kind: str = EDGE_NORMAL) -> None:
        pair = (other, kind)
        if pair not in self.succs:
            self.succs.append(pair)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        what = self.label or type(self.stmt).__name__
        line = getattr(self.stmt, "lineno", "?")
        return f"<Node {what}@{line} ->{len(self.succs)}>"


class CFG:
    def __init__(self, entry: Node, exit_: Node, exc_exit: Node) -> None:
        self.entry = entry
        self.exit = exit_
        self.exc_exit = exc_exit
        self.nodes: list[Node] = []


def can_raise(node: ast.AST | None) -> bool:
    """Conservative: an expression that calls, subscripts (a Load —
    KeyError/IndexError are routine; a Store into a dict cannot
    realistically fail), awaits, raises or asserts can raise. Plain
    name/constant shuffling cannot (close enough — attribute access on
    project dataclasses does not realistically fail, and treating it
    as raising would fabricate an exception path out of every
    statement). Nested def/lambda bodies are deferred code — a `def`
    whose body calls cannot raise at the definition statement."""
    if node is None:
        return False
    for sub in walk_scope(node):
        if isinstance(sub, (ast.Call, ast.Raise, ast.Assert, ast.Await,
                            ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(sub, ast.Subscript) and not isinstance(
                sub.ctx, ast.Store):
            return True
    return False


class _Ctx:
    """Where non-local control transfers land from the current point:
    raising statements (`exc`), `return` (`ret`), `break`/`continue`
    (`brk`/`cont`, None outside loops)."""

    __slots__ = ("exc", "ret", "brk", "cont")

    def __init__(self, exc: Node, ret: Node,
                 brk: Node | None = None, cont: Node | None = None) -> None:
        self.exc = exc
        self.ret = ret
        self.brk = brk
        self.cont = cont

    def replace(self, **kw: Any) -> "_Ctx":
        vals = {s: getattr(self, s) for s in self.__slots__}
        vals.update(kw)
        return _Ctx(**vals)


# A "frontier" is the set of dangling (node, edge_kind) pairs whose
# next normal successor is whatever statement comes next.
_Frontier = list[tuple[Node, str]]


class _Builder:
    def __init__(self) -> None:
        self.nodes: list[Node] = []

    def new(self, **kw: Any) -> Node:
        node = Node(**kw)
        self.nodes.append(node)
        return node

    def _connect(self, preds: _Frontier, node: Node) -> None:
        for p, kind in preds:
            p.edge(node, kind)

    def seq(self, stmts: Iterable[ast.stmt], preds: _Frontier,
            ctx: _Ctx) -> _Frontier:
        for s in stmts:
            preds = self.stmt(s, preds, ctx)
        return preds

    # ------------------------------------------------------------ statements

    def stmt(self, s: ast.stmt, preds: _Frontier, ctx: _Ctx) -> _Frontier:
        if isinstance(s, ast.If):
            return self._if(s, preds, ctx)
        if isinstance(s, (ast.While,)):
            return self._while(s, preds, ctx)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return self._for(s, preds, ctx)
        if isinstance(s, ast.Try) or s.__class__.__name__ == "TryStar":
            return self._try(s, preds, ctx)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self._with(s, preds, ctx)
        if isinstance(s, ast.Return):
            node = self.new(stmt=s, expr=s.value)
            self._connect(preds, node)
            if can_raise(s.value):
                node.edge(ctx.exc, EDGE_EXC)
            node.edge(ctx.ret, EDGE_NORMAL)
            return []
        if isinstance(s, ast.Raise):
            node = self.new(stmt=s, expr=s)
            self._connect(preds, node)
            node.edge(ctx.exc, EDGE_EXC)
            return []
        if isinstance(s, ast.Break):
            node = self.new(stmt=s)
            self._connect(preds, node)
            node.edge(ctx.brk if ctx.brk is not None else ctx.ret,
                      EDGE_NORMAL)
            return []
        if isinstance(s, ast.Continue):
            node = self.new(stmt=s)
            self._connect(preds, node)
            node.edge(ctx.cont if ctx.cont is not None else ctx.ret,
                      EDGE_NORMAL)
            return []
        # Plain statement (assignment, expression, def, pass, ...).
        node = self.new(stmt=s, expr=s)
        self._connect(preds, node)
        if can_raise(s):
            node.edge(ctx.exc, EDGE_EXC)
        return [(node, EDGE_NORMAL)]

    def _if(self, s: ast.If, preds: _Frontier, ctx: _Ctx) -> _Frontier:
        node = self.new(stmt=s, expr=s.test, test=s.test)
        self._connect(preds, node)
        if can_raise(s.test):
            node.edge(ctx.exc, EDGE_EXC)
        out = self.seq(s.body, [(node, EDGE_TRUE)], ctx)
        if s.orelse:
            out += self.seq(s.orelse, [(node, EDGE_FALSE)], ctx)
        else:
            out.append((node, EDGE_FALSE))
        return out

    def _while(self, s: ast.While, preds: _Frontier, ctx: _Ctx) -> _Frontier:
        head = self.new(stmt=s, expr=s.test, test=s.test)
        self._connect(preds, head)
        if can_raise(s.test):
            head.edge(ctx.exc, EDGE_EXC)
        join = self.new(label="loop-exit")
        body_ctx = ctx.replace(brk=join, cont=head)
        body_out = self.seq(s.body, [(head, EDGE_TRUE)], body_ctx)
        self._connect(body_out, head)
        exit_preds: _Frontier = [(head, EDGE_FALSE)]
        if s.orelse:
            exit_preds = self.seq(s.orelse, exit_preds, ctx)
        self._connect(exit_preds, join)
        return [(join, EDGE_NORMAL)]

    def _for(self, s: ast.For | ast.AsyncFor, preds: _Frontier,
             ctx: _Ctx) -> _Frontier:
        head = self.new(stmt=s, expr=s.iter)   # no narrowable test
        self._connect(preds, head)
        if can_raise(s.iter):
            head.edge(ctx.exc, EDGE_EXC)
        join = self.new(label="loop-exit")
        body_ctx = ctx.replace(brk=join, cont=head)
        body_out = self.seq(s.body, [(head, EDGE_TRUE)], body_ctx)
        self._connect(body_out, head)
        exit_preds: _Frontier = [(head, EDGE_FALSE)]
        if s.orelse:
            exit_preds = self.seq(s.orelse, exit_preds, ctx)
        self._connect(exit_preds, join)
        return [(join, EDGE_NORMAL)]

    def _with(self, s: ast.With | ast.AsyncWith, preds: _Frontier,
              ctx: _Ctx) -> _Frontier:
        # Context-manager protocol approximated: entering can raise,
        # the body runs, exits propagate (managers that swallow
        # exceptions are not modeled — none of the scoped protocols
        # hide behind one).
        for item in s.items:
            node = self.new(stmt=s, expr=item.context_expr)
            self._connect(preds, node)
            if can_raise(item.context_expr):
                node.edge(ctx.exc, EDGE_EXC)
            preds = [(node, EDGE_NORMAL)]
        return self.seq(s.body, preds, ctx)

    def _try(self, s: ast.Try, preds: _Frontier, ctx: _Ctx) -> _Frontier:
        if not s.finalbody:
            return self._try_core(s, preds, ctx)
        # finally: every way OUT of the protected region detours
        # through its own CLONE of the finally body, then continues to
        # the original target. Cloning (rather than a join node) keeps
        # per-path states separate — the whole point of the analysis.
        clones: dict[tuple[int, str], Node] = {}

        def fin(target: Node | None, kind: str) -> Node | None:
            if target is None:
                return None
            key = (id(target), kind)
            if key not in clones:
                entry = self.new(label="finally")
                out = self.seq(s.finalbody, [(entry, EDGE_NORMAL)], ctx)
                if kind == EDGE_EXC:
                    # The re-raise happens AFTER the finally body runs
                    # to completion: keep the clone's internal edge
                    # kinds intact (a t/f edge must stay narrowable —
                    # `finally: if h is not None: h.release()` relies
                    # on it) and mark only the final hop exceptional.
                    join = self.new(label="finally-reraise")
                    self._connect(out, join)
                    join.edge(target, EDGE_EXC)
                else:
                    for n, k in out:
                        n.edge(target, k)
                clones[key] = entry
            return clones[key]

        inner_ctx = _Ctx(
            exc=fin(ctx.exc, EDGE_EXC),
            ret=fin(ctx.ret, EDGE_NORMAL),
            brk=fin(ctx.brk, EDGE_NORMAL),
            cont=fin(ctx.cont, EDGE_NORMAL),
        )
        out = self._try_core(s, preds, inner_ctx)
        entry = self.new(label="finally")
        self._connect(out, entry)
        return self.seq(s.finalbody, [(entry, EDGE_NORMAL)], ctx)

    def _try_core(self, s: ast.Try, preds: _Frontier,
                  ctx: _Ctx) -> _Frontier:
        if not s.handlers:
            body_out = self.seq(s.body, preds, ctx)
            if s.orelse:
                body_out = self.seq(s.orelse, body_out, ctx)
            return body_out
        dispatch = self.new(label="exc-dispatch")
        body_ctx = ctx.replace(exc=dispatch)
        body_out = self.seq(s.body, preds, body_ctx)
        if s.orelse:
            body_out = self.seq(s.orelse, body_out, ctx)
        out = body_out
        catch_all = False
        for h in s.handlers:
            names = _handler_names(h)
            if h.type is None or names & _CATCH_ALL_NAMES:
                catch_all = True
            hnode = self.new(stmt=h, label="except")
            dispatch.edge(hnode, EDGE_NORMAL)
            out = out + self.seq(h.body, [(hnode, EDGE_NORMAL)], ctx)
        if not catch_all:
            # The exception may match no handler and keep propagating.
            dispatch.edge(ctx.exc, EDGE_EXC)
        return out


def _handler_names(h: ast.ExceptHandler) -> set[str]:
    if h.type is None:
        return set()
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    names: set[str] = set()
    for t in types:
        # `except exc.SomeError` — the leaf attr is the class name.
        if isinstance(t, ast.Attribute):
            names.add(t.attr)
        elif isinstance(t, ast.Name):
            names.add(t.id)
    return names


def build_cfg(func: ast.FunctionDef | ast.AsyncFunctionDef) -> CFG:
    b = _Builder()
    entry = b.new(label="entry")
    exit_ = b.new(label="exit")
    exc_exit = b.new(label="exc-exit")
    ctx = _Ctx(exc=exc_exit, ret=exit_)
    out = b.seq(func.body, [(entry, EDGE_NORMAL)], ctx)
    for n, k in out:
        n.edge(exit_, k)
    cfg = CFG(entry, exit_, exc_exit)
    cfg.nodes = b.nodes
    return cfg


# ---------------------------------------------------------------- walker


def analyze(func: ast.FunctionDef | ast.AsyncFunctionDef, semantics: Any,
            max_states_per_node: int = 96,
            max_steps: int = 40_000) -> list[Any]:
    """Push `semantics` states through `func`'s CFG to a bounded
    fixpoint; returns the deduplicated, sorted findings."""
    cfg = build_cfg(func)
    findings: set[Any] = set()
    seen: dict[int, set[Any]] = {}
    init = semantics.initial()
    work: deque[tuple[Node, Any]] = deque([(cfg.entry, init)])
    seen[id(cfg.entry)] = {init}
    steps = 0
    while work and steps < max_steps:
        steps += 1
        node, st = work.popleft()
        if node is cfg.exit:
            findings.update(semantics.at_exit(st, False))
            continue
        if node is cfg.exc_exit:
            findings.update(semantics.at_exit(st, True))
            continue
        post, exc_st = st, st
        if node.stmt is not None:
            post, exc_st, fs = semantics.transfer(node, st)
            findings.update(fs)
        for succ, kind in node.succs:
            if kind == EDGE_EXC:
                nxt = exc_st
            elif kind in (EDGE_TRUE, EDGE_FALSE):
                nxt = semantics.on_branch(node.test, post,
                                          kind == EDGE_TRUE)
                if nxt is None:
                    continue
            else:
                nxt = post
            bucket = seen.setdefault(id(succ), set())
            if nxt not in bucket and len(bucket) < max_states_per_node:
                bucket.add(nxt)
                work.append((succ, nxt))
    return sorted(findings)


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef
                                              | ast.AsyncFunctionDef]:
    """Every def in the module, methods and nested defs included (each
    is analyzed as its own scope)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ------------------------------------------------------- shared helpers


# The trackable-variable identity both flow checkers share: `a.b.c`
# for a Name/Attribute chain, None for anything computed. One
# implementation for the whole package — core.dotted_name.
dotted_path = dotted_name


def walk_scope(node: ast.AST | None) -> Iterator[ast.AST]:
    """ast.walk restricted to THIS execution scope: does not descend
    into nested def/lambda bodies (deferred code — their calls run
    when the closure runs, not at the definition statement; each
    nested def is analyzed as its own scope by iter_functions).
    Decorators and argument defaults DO evaluate at the definition, so
    those subtrees are walked."""
    if node is None:
        return
    todo = deque([node])
    while todo:
        n = todo.popleft()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not node:
            if not isinstance(n, ast.Lambda):
                todo.extend(n.decorator_list)
            todo.extend(d for d in n.args.defaults + n.args.kw_defaults
                        if d is not None)
        else:
            todo.extend(ast.iter_child_nodes(n))


def assigned_paths(stmt: ast.AST) -> set[str]:
    """Every Name/Attribute dotted path this statement (re)binds:
    Assign/AnnAssign/AugAssign targets (tuple targets unpacked),
    for-loop targets, with ... as targets."""
    out: set[str] = set()

    def collect(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)
        else:
            p = dotted_path(t)
            if p is not None:
                out.add(p)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return out
