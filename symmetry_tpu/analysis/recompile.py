"""Recompile-hazard checker: compile-cache stability inside jit traces.

The serving engine's core contract is ZERO steady-state recompiles:
every prefill/decode/verify dispatch must hit a compiled program from
the warmup grid (tests assert `engine.compile_cache_sizes()` is flat
across traffic). The hazards that break it are all Python-level and
all statically visible inside a jit-traced function:

  R301  `int()` / `float()` / `bool()` / `.item()` / `.tolist()` on a
        traced value — forces a device→host sync at trace time and
        bakes the VALUE into the compiled program, so every new value
        is a new compile
  R302  `if` / `while` on a traced value — a data-dependent Python
        branch; each branch outcome traces (and compiles) its own
        program (shape/dtype/ndim predicates are fine: those are
        static under jit)
  R303  `np.asarray` / `np.array` / `jax.device_get` on a traced
        value — a silent host round-trip inside the trace

Traced functions are found two ways, matching how the engine builds
its programs:

  - decorated: `@jax.jit`, `@partial(jax.jit, static_argnames=…)`,
    `@functools.partial(jax.jit, …)`
  - wrapped at call sites: `jax.jit(fn, …)` where `fn` is a function
    defined anywhere in the same module (the engine's
    `self._prefill = jax.jit(prefill, donate_argnums=…)` pattern)

Static arguments (`static_argnames` / `static_argnums`) are exempt
from taint: branching on them is exactly what they are for. Taint then
flows forward through local assignments; `.shape`, `.ndim`, `.dtype`,
`.size` and `len()` sanitize, because those are Python values at trace
time.
"""

from __future__ import annotations

import ast

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    SourceFile,
    call_name,
    const_str,
    dotted_name,
)

NAME = "recompile-hazard"

# Scope: the jit-traced tiers (ISSUE list). The network/server tiers
# never trace; tests trace deliberately-weird shapes on purpose.
SCOPE = (
    "symmetry_tpu/engine/engine.py",
    "symmetry_tpu/engine/spec/*.py",
    "symmetry_tpu/ops/*.py",
    "symmetry_tpu/models/*.py",
)

_SANITIZING_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                     "sharding", "weak_type"}
_SANITIZING_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                     "range", "min", "max", "enumerate", "zip"}
# min/max over shape ints stay static; over tracers they return tracers,
# but flagging them would drown the real findings — the converging
# int()/branch site downstream still flags.
_VALUE_SYNC_CALLS = {"int", "float", "bool"}
_VALUE_SYNC_METHODS = {"item", "tolist"}
_HOST_PULL_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "onp.asarray", "onp.array",
                    "jax.device_get"}


def _jit_static(call: ast.Call) -> tuple[set[str], set[int]]:
    """static_argnames / static_argnums sets from a jit(…) call."""
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                s = const_str(v)
                if s is not None:
                    names.add(s)
        elif kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.add(v.value)
    return names, nums


def _is_jit_name(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _find_traced(sf: SourceFile) -> list[tuple[ast.AST, set[str]]]:
    """(FunctionDef, static param names) for every function the module
    traces under jit. Keyed by NODE identity, not name: two builder
    methods each defining a nested `def step` and jit-wrapping it are
    two distinct traced functions — a name-keyed registry would analyze
    the first and silently skip the second."""
    # All function defs in the module, grouped by name (nested included
    # — the engine defines its programs inside builder methods).
    defs_by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: dict[int, tuple[ast.AST, set[str]]] = {}

    def param_names(fn: ast.AST) -> list[str]:
        a = fn.args
        return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]

    def add(fn: ast.AST, names: set[str], nums: set[int]) -> None:
        params = param_names(fn)
        static = set(names)
        for i in nums:
            if 0 <= i < len(params):
                static.add(params[i])
        traced[id(fn)] = (fn, static)

    # Decorated defs.
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            if _is_jit_name(dec):
                add(node, set(), set())
            elif isinstance(dec, ast.Call):
                cn = call_name(dec)
                if cn in ("functools.partial", "partial") and dec.args \
                        and _is_jit_name(dec.args[0]):
                    names, nums = _jit_static(dec)
                    add(node, names, nums)
                elif _is_jit_name(dec.func):
                    names, nums = _jit_static(dec)
                    add(node, names, nums)
    # Call-site wrapping: jax.jit(fn, …). A name can resolve to several
    # defs (same-named program builders in different scopes); every one
    # is analyzed — over-approximating beats silently skipping the
    # second definition.
    for node in ast.walk(sf.tree):
        if (isinstance(node, ast.Call) and _is_jit_name(node.func)
                and node.args and isinstance(node.args[0], ast.Name)):
            for fn in defs_by_name.get(node.args[0].id, ()):
                if id(fn) not in traced:
                    names, nums = _jit_static(node)
                    add(fn, names, nums)
    return list(traced.values())


class _TaintWalker:
    """Forward taint pass over one traced function body. Deliberately
    simple: once a local is tainted it stays tainted (loops/branches
    join conservatively) unless reassigned from a clean expression."""

    def __init__(self, sf: SourceFile, fn: ast.AST,
                 static: set[str]) -> None:
        self.sf = sf
        self.fn = fn
        a = fn.args
        params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        self.tainted: set[str] = {p for p in params if p not in static}
        self.findings: list[Finding] = []

    # ----------------------------------------------------- expressions

    def taint(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _SANITIZING_ATTRS:
                return False
            return self.taint(node.value)
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn in _SANITIZING_CALLS:
                return False
            # method calls on a tainted receiver stay tainted; any
            # tainted argument taints the result
            parts = ([node.func.value] if isinstance(node.func,
                                                     ast.Attribute)
                     else [])
            return any(self.taint(x) for x in
                       parts + list(node.args)
                       + [kw.value for kw in node.keywords])
        if isinstance(node, ast.Subscript):
            return self.taint(node.value) or self.taint(node.slice)
        if isinstance(node, (ast.BinOp,)):
            return self.taint(node.left) or self.taint(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.taint(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is an argument-STRUCTURE
            # predicate — static at trace time, not a value branch.
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return self.taint(node.left) or any(
                self.taint(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return any(self.taint(x)
                       for x in (node.test, node.body, node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.taint(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        if isinstance(node, ast.JoinedStr):
            return False
        return False

    def _emit(self, code: str, node: ast.AST, msg: str,
              symbol: str) -> None:
        self.findings.append(Finding(
            checker=NAME, code=code, path=self.sf.rel,
            line=node.lineno, message=msg,
            symbol=f"{self.fn.name}:{symbol}"))

    def _scan_calls(self, node: ast.AST) -> None:
        """R301/R303 call hazards anywhere inside one statement."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            cn = call_name(sub)
            if (cn in _VALUE_SYNC_CALLS and sub.args
                    and self.taint(sub.args[0])):
                self._emit(
                    "R301", sub,
                    f"{cn}() on a traced value inside jit function "
                    f"'{self.fn.name}' — bakes the value into the "
                    f"compiled program (one compile per value)", cn)
            elif (isinstance(sub.func, ast.Attribute)
                  and sub.func.attr in _VALUE_SYNC_METHODS
                  and self.taint(sub.func.value)):
                self._emit(
                    "R301", sub,
                    f".{sub.func.attr}() on a traced value inside jit "
                    f"function '{self.fn.name}' — device→host sync at "
                    f"trace time", f".{sub.func.attr}")
            elif cn in _HOST_PULL_CALLS and sub.args \
                    and self.taint(sub.args[0]):
                self._emit(
                    "R303", sub,
                    f"{cn}() on a traced value inside jit function "
                    f"'{self.fn.name}' — host round-trip inside the "
                    f"trace", cn)

    # ------------------------------------------------------ statements

    def run(self) -> list[Finding]:
        for stmt in self.fn.body:
            self.stmt(stmt)
        return self.findings

    def stmt(self, node: ast.AST) -> None:
        if not isinstance(node, (ast.If, ast.While, ast.For, ast.With,
                                 ast.Try, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            # Simple statement: hazard-scan its expressions once.
            # (Compound statements scan only their header expressions
            # here and recurse into bodies statement-by-statement, so
            # nothing is scanned twice.)
            self._scan_calls(node)
        if isinstance(node, (ast.If, ast.While)):
            self._scan_calls(node.test)
            if self.taint(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                self._emit(
                    "R302", node,
                    f"data-dependent `{kind}` on a traced value inside "
                    f"jit function '{self.fn.name}' — each outcome "
                    f"traces its own program; use lax.cond/select or a "
                    f"static argument", kind)
            for child in node.body + node.orelse:
                self.stmt(child)
            return
        if isinstance(node, ast.Assign):
            val_taint = self.taint(node.value)
            for t in node.targets:
                self._bind(t, val_taint)
            return
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            self._bind(node.target, self.taint(node.value))
            return
        if isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                if self.taint(node.value):
                    self.tainted.add(node.target.id)
            return
        if isinstance(node, ast.For):
            self._scan_calls(node.iter)
            self._bind(node.target, self.taint(node.iter))
            for child in node.body + node.orelse:
                self.stmt(child)
            return
        if isinstance(node, ast.With):
            for item in node.items:
                self._scan_calls(item.context_expr)
            for child in node.body:
                self.stmt(child)
            return
        if isinstance(node, ast.Try):
            for child in (node.body + node.orelse + node.finalbody
                          + [s for h in node.handlers for s in h.body]):
                self.stmt(child)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested helper: its body traces too when called, with the
            # enclosing scope visible — keep walking with shared taint.
            for child in node.body:
                self.stmt(child)
            return

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tainted)


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.select(SCOPE):
        for fn, static in _find_traced(sf):
            findings.extend(_TaintWalker(sf, fn, static).run())
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="value syncs / data-dependent branches inside jit traces",
    run=check,
    codes=("R301", "R302", "R303"),
)
