"""Concurrency checker: cross-thread mutation and async-blocking calls.

The system is two concurrency regimes glued together: a threaded engine
tier (scheduler loop, engine-host pipe reader, multihost command loop)
and an asyncio provider/client/server tier. Each has one
characteristic failure this checker makes static:

  C201  blocking call inside an `async def` body — `time.sleep`, sync
        subprocess APIs, `Future.result()`, sync socket connects: each
        stalls the WHOLE event loop, which in this codebase means every
        client's stream at once (the exact pathology that forced the
        engine out of the provider process; see engine/host.py)
  C202  attribute mutated from more than one thread entry point with at
        least one mutation site not under a lock — the lost-update race
        on shared counters/maps
  C203  an UNBOUNDED queue.Queue/SimpleQueue used as a cross-thread
        channel (put sites and get sites reachable from different
        thread contexts) — a consumer that stalls lets the producer
        grow it without limit, which in a serving process is an OOM
        with a delay fuse. Bounded construction (any nonzero maxsize)
        is the fix: the blocking put IS the backpressure. Queues whose
        depth is bounded upstream (a provider admission cap, a
        handshake window) are baseline entries with that argument
        written down. asyncio.Queue is exempt — its producers and
        consumers share the loop thread, and flow control there is the
        loop's problem, not a thread-safety one.

Thread entry points are inferred per class:

  - methods passed as `threading.Thread(target=self.X)` targets
  - methods whose bound reference ESCAPES the class without being
    called (`emit_batch=self._emit_batch`, `handoff=self._handoff_sink`)
    — a callback handed to other machinery runs on that machinery's
    thread, which is exactly how the scheduler calls back into the
    engine host

Entry contexts propagate through the intra-class `self.foo()` call
graph; public methods are additionally reachable from "main" (any
caller thread). A mutation site counts as locked when it sits
lexically inside `with self.<something-lock-ish>:`. `__init__` is
exempt — nothing else is running yet.

The checker is deliberately an over-approximation: a per-request dict
key that is only ever touched by one thread at a time still flags.
Those are baseline entries with the ownership argument written down —
which is the point: the invariant is now stated somewhere a reviewer
(and the next refactor) can see it.
"""

from __future__ import annotations

import ast

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    SourceFile,
    call_name,
    dotted_name,
)

NAME = "concurrency"

# Scope: the whole package. Tools and tests host no long-lived threads
# worth modeling and drive event loops synchronously on purpose.
SCOPE = ("symmetry_tpu/**",)

# C201: dotted callee names that block the calling thread. Methods that
# cannot be resolved statically (bare `.recv()` etc.) are left alone —
# the checker prefers silence to noise.
BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput", "subprocess.Popen",
    "socket.create_connection", "socket.getaddrinfo",
    "os.system", "os.waitpid", "os.wait",
    "requests.get", "requests.post", "requests.put", "requests.request",
    "urllib.request.urlopen",
}

# Zero-arg method names that block when called on a concurrent.futures
# future / thread handle inside async code.
BLOCKING_METHODS = {"result"}

_LOCKISH = ("lock", "mutex", "cond")

# Container methods that mutate their receiver in place — the
# `self.stats.update(...)` / `self._cancelled.discard(...)` mutation
# shapes Assign/AugAssign extraction cannot see. Queue/deque handoff
# verbs (put/get/popleft…) are deliberately absent: those types are the
# codebase's sanctioned cross-thread channels and flagging them would
# drown the real races.
_MUTATOR_METHODS = {"append", "add", "pop", "remove", "discard", "clear",
                    "update", "extend", "insert", "setdefault", "popitem"}

# C203: thread-queue constructors. asyncio.Queue is excluded at the
# call-name level (loop-internal flow control, not a thread channel).
_QUEUE_TYPES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}

# Queue handoff verbs, split by side: the channel counts as cross-thread
# when put-side and get-side contexts are not the same single thread.
_QUEUE_PUTS = {"put", "put_nowait"}
_QUEUE_GETS = {"get", "get_nowait"}


def _queue_bounded(call: ast.Call, leaf: str) -> bool:
    """Is this queue construction bounded? SimpleQueue has no maxsize at
    all. Queue's maxsize (first positional or keyword) bounds it iff
    positive; absent or constant <= 0 means infinite. A COMPUTED
    maxsize (maxsize=max(1, n)) is taken as bounded — the checker
    prefers silence to noise on expressions it cannot evaluate."""
    if leaf == "SimpleQueue":
        return False
    size: ast.AST | None = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    if size is None:
        return False
    if isinstance(size, ast.Constant):
        return isinstance(size.value, (int, float)) and size.value > 0
    return True


def _lock_name(expr: ast.AST) -> str | None:
    """The identity of a lock-ish `with` context expression, else None.
    Identity matters: two sites holding DIFFERENT locks do not exclude
    each other."""
    dn = dotted_name(expr)
    if dn is None and isinstance(expr, ast.Call):
        dn = call_name(expr)
    if dn is None:
        return None
    leaf = dn.split(".")[-1].lower()
    return dn if any(tok in leaf for tok in _LOCKISH) else None


# ------------------------------------------------------------------ C201


def _check_async_blocking(sf: SourceFile) -> list[Finding]:
    findings: list[Finding] = []

    def visit_async_body(fn: ast.AsyncFunctionDef) -> None:
        # Walk the async body but do not descend into nested defs: a
        # sync helper defined inside (e.g. shipped to a thread pool via
        # run_in_executor / to_thread) is allowed to block, and a
        # nested ASYNC def gets its own visit from the module walk —
        # descending here would double-report its findings.
        stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn is not None and (
                        cn in BLOCKING_CALLS
                        or any(cn.endswith("." + b)
                               for b in BLOCKING_CALLS)):
                    findings.append(Finding(
                        checker=NAME, code="C201", path=sf.rel,
                        line=node.lineno, symbol=f"{fn.name}:{cn}",
                        message=(f"blocking call {cn}() inside "
                                 f"async def {fn.name} — stalls the "
                                 f"whole event loop; use the asyncio "
                                 f"equivalent or run_in_executor")))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in BLOCKING_METHODS
                      and not node.args
                      and all(kw.arg == "timeout"
                              for kw in node.keywords)
                      and not isinstance(
                          getattr(node, "sym_parent", None), ast.Await)):
                    findings.append(Finding(
                        checker=NAME, code="C201", path=sf.rel,
                        line=node.lineno,
                        symbol=f"{fn.name}:.{node.func.attr}",
                        message=(f".{node.func.attr}() inside async def "
                                 f"{fn.name} blocks the event loop if "
                                 f"the receiver is a concurrent.futures "
                                 f"handle — await it instead")))
            stack.extend(ast.iter_child_nodes(node))

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            visit_async_body(node)
    return findings


# ------------------------------------------------------------------ C202


class _ClassModel:
    def __init__(self, cls: ast.ClassDef) -> None:
        self.cls = cls
        self.methods: dict[str, ast.AST] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
        self.roots: set[str] = set()       # foreign-thread entry methods
        # Escaped local closures: a `def emit(…)` / thunk defined inside
        # a method and handed to other machinery (the scheduler, an
        # executor) runs on THAT machinery's thread. pseudo-entry name →
        # defining method.
        self.escaped_closures: dict[str, str] = {}
        self.calls: dict[str, set[str]] = {}   # context -> self-calls
        # mutation unit -> list of (context, line, held-lock names)
        self.mutations: dict[str, list[tuple[str, int,
                                             frozenset[str]]]] = {}
        # C203: queue attr -> (construction line, bounded?), and
        # queue attr -> [(side "put"/"get", context, line)]. Ops are
        # recorded for EVERY attr that quacks like a queue and filtered
        # against the constructed set at verdict time, so dict .get()
        # noise never reaches a finding.
        self.queues: dict[str, tuple[int, bool]] = {}
        self.queue_ops: dict[str, list[tuple[str, str, int]]] = {}

    def contexts(self) -> dict[str, set[str]]:
        """Entry-context sets per context (method or escaped closure):
        thread roots and escaped closures seed their own label, public
        methods seed "main"; labels flow caller→callee through the
        self-call graph to a fixpoint."""
        ctx: dict[str, set[str]] = {name: set() for name in self.methods}
        for name in self.methods:
            if name in self.roots:
                ctx[name].add(f"thread:{name}")
            if not name.startswith("_"):
                ctx[name].add("main")
        for pseudo in self.escaped_closures:
            ctx[pseudo] = {f"closure:{pseudo}"}
        changed = True
        while changed:
            changed = False
            for caller, callees in self.calls.items():
                for callee in callees:
                    if callee not in ctx or caller not in ctx:
                        continue
                    before = len(ctx[callee])
                    ctx[callee] |= ctx[caller]
                    changed = changed or len(ctx[callee]) != before
        return ctx


def _self_attr(node: ast.AST) -> str | None:
    """`attr` for `self.attr` (one level only)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _mutated_attr(target: ast.AST) -> str | None:
    """The mutation unit a store target touches. Key-granular for
    constant subscripts — `self.metrics["requests"] += 1` races with
    other writers of `metrics["requests"]`, not with the engine
    thread's `metrics["tokens"]` (dict item ops are GIL-atomic per
    key) — attr-granular for plain stores and dynamic keys."""
    if isinstance(target, ast.Subscript):
        attr = _self_attr(target.value)
        if attr is None:
            return None
        key = (target.slice.value
               if isinstance(target.slice, ast.Constant)
               and isinstance(target.slice.value, (str, int))
               else None)
        return f"{attr}[{key!r}]" if key is not None else attr
    return _self_attr(target)


def _build_model(cls: ast.ClassDef) -> _ClassModel:
    model = _ClassModel(cls)
    for name, fn in model.methods.items():
        # Local functions whose NAME escapes the method (referenced
        # other than as a direct callee — passed as a callback, stored
        # on a request object): their bodies run in whatever context
        # the receiver calls them from, which in this codebase means
        # another thread more often than not.
        nested: dict[str, ast.AST] = {}
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.setdefault(sub.name, sub)
        escaped: set[str] = set()
        for sub in ast.walk(fn):
            if (isinstance(sub, ast.Name) and sub.id in nested
                    and isinstance(sub.ctx, ast.Load)):
                parent = getattr(sub, "sym_parent", None)
                if not (isinstance(parent, ast.Call)
                        and parent.func is sub):
                    escaped.add(sub.id)
        pseudo_of: dict[ast.AST, str] = {}
        for dname in escaped:
            pname = f"{name}.<{dname}>"
            model.escaped_closures[pname] = name
            pseudo_of[nested[dname]] = pname

        def walk(node: ast.AST, held: frozenset, owner: str,
                 pseudo_of: dict[ast.AST, str] = pseudo_of) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node in pseudo_of:
                inner_owner = pseudo_of[node]
                for child in node.body:
                    walk(child, frozenset(), inner_owner)
                return
            if isinstance(node, ast.With):
                inner = held | {n for n in (
                    _lock_name(item.context_expr)
                    for item in node.items) if n is not None}
                for item in node.items:
                    walk(item.context_expr, held, owner)
                for child in node.body:
                    walk(child, inner, owner)
                return
            if isinstance(node, ast.Call):
                # threading.Thread(target=self.x) → root
                cn = call_name(node)
                if cn is not None and cn.split(".")[-1] == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            t = _self_attr(kw.value)
                            if t in model.methods:
                                model.roots.add(t)
                # self.foo(...) → call edge from the current context
                callee = _self_attr(node.func)
                if callee in model.methods:
                    model.calls.setdefault(owner, set()).add(callee)
                # self.x.update(...) / self._s.discard(...) — in-place
                # container mutation through a method call
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATOR_METHODS):
                    unit = _mutated_attr(node.func.value)
                    if unit is not None:
                        model.mutations.setdefault(unit, []).append(
                            (owner, node.lineno, held))
                # self.q.put(...) / self.q.get(...) — queue handoff
                # sites for the C203 cross-thread-channel verdict
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in (_QUEUE_PUTS | _QUEUE_GETS)):
                    unit = _self_attr(node.func.value)
                    if unit is not None:
                        side = ("put" if node.func.attr in _QUEUE_PUTS
                                else "get")
                        model.queue_ops.setdefault(unit, []).append(
                            (side, owner, node.lineno))
            if isinstance(node, ast.Attribute):
                # a bound-method reference that is NOT the callee of a
                # call escapes → foreign-context entry point. Async
                # methods are exempt: a coroutine handed out as a
                # callback still runs on the event loop's one thread.
                attr = _self_attr(node)
                parent = getattr(node, "sym_parent", None)
                is_callee = (isinstance(parent, ast.Call)
                             and parent.func is node)
                if (attr in model.methods and not is_callee
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(model.methods[attr],
                                       ast.FunctionDef)):
                    model.roots.add(attr)
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                # self.q = queue.Queue(...) — remember the channel and
                # whether its construction bounded it (C203)
                value = getattr(node, "value", None)
                if isinstance(value, ast.Call):
                    cn = call_name(value)
                    leaf = cn.split(".")[-1] if cn else ""
                    if (leaf in _QUEUE_TYPES and cn is not None
                            and "asyncio" not in cn):
                        qtargets = (node.targets
                                    if isinstance(node, ast.Assign)
                                    else [node.target])
                        for t in qtargets:
                            qattr = _self_attr(t)
                            if qattr is not None:
                                model.queues[qattr] = (
                                    node.lineno,
                                    _queue_bounded(value, leaf))
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
                    for elt in elts:
                        attr = _mutated_attr(elt)
                        if attr is not None:
                            model.mutations.setdefault(attr, []).append(
                                (owner, elt.lineno, held))
            for child in ast.iter_child_nodes(node):
                walk(child, held, owner)

        for stmt in (fn.body if hasattr(fn, "body") else []):
            walk(stmt, frozenset(), name)
    return model


def _check_cross_thread(sf: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        model = _build_model(node)
        if not model.roots and not model.escaped_closures:
            continue  # single-context class: nothing to race with
        ctx = model.contexts()
        # Whole-container mutation (self.stats.update(…), self.stats =
        # …) races with EVERY key-granular write of the same attribute:
        # fold the attr-level sites into each of its key units so the
        # two granularities collide instead of passing each other by.
        mutations = dict(model.mutations)
        for unit, sites in model.mutations.items():
            if "[" in unit:
                base = unit.split("[", 1)[0]
                if base in model.mutations:
                    mutations[unit] = sites + model.mutations[base]
        for attr, sites in mutations.items():
            live = [(m, ln, held) for m, ln, held in sites
                    if m != "__init__"]
            labels: set[str] = set()
            for method, _line, _held in live:
                labels |= ctx.get(method, set())
            if len(labels) < 2:
                continue
            # Protected only if ONE COMMON lock is held at every
            # site — different locks do not exclude each other.
            common = None
            for _m, _ln, held in live:
                common = held if common is None else common & held
            if common:
                continue
            unlocked = sorted((m, ln) for m, ln, held in live
                              if not held)
            if unlocked:
                problem = f"{len(unlocked)} unlocked site(s)"
            else:
                # Every site holds SOME lock, but no single lock is
                # common to all — "unlocked" would send the reader
                # hunting for a `with` that is already there.
                unlocked = sorted((m, ln) for m, ln, _h in live)
                problem = (f"no common lock across its "
                           f"{len(unlocked)} sites (different locks "
                           f"do not exclude each other)")
            m, ln = unlocked[0]
            findings.append(Finding(
                checker=NAME, code="C202", path=sf.rel, line=ln,
                symbol=f"{node.name}.{attr}",
                message=(f"self.{attr} is mutated from "
                         f"{len(labels)} thread contexts "
                         f"({', '.join(sorted(labels))}) with "
                         f"{problem} "
                         f"(first: {node.name}.{m}) — guard with a "
                         f"lock or record the ownership argument in "
                         f"the baseline")))
        # C203: an unbounded queue whose put side and get side are
        # reachable from different thread contexts is a cross-thread
        # channel with no backpressure.
        for attr, (line, bounded) in sorted(model.queues.items()):
            if bounded:
                continue
            put_labels: set[str] = set()
            get_labels: set[str] = set()
            for side, owner, _ln in model.queue_ops.get(attr, []):
                labels = ctx.get(owner, set())
                if side == "put":
                    put_labels |= labels
                else:
                    get_labels |= labels
            if not put_labels or not get_labels:
                continue
            if len(put_labels | get_labels) < 2:
                continue  # one thread talking to itself: no backlog race
            findings.append(Finding(
                checker=NAME, code="C203", path=sf.rel, line=line,
                symbol=f"{node.name}.{attr}",
                message=(f"self.{attr} is an unbounded queue crossing "
                         f"thread contexts (put: "
                         f"{', '.join(sorted(put_labels))}; get: "
                         f"{', '.join(sorted(get_labels))}) — a stalled "
                         f"consumer grows it without limit; construct "
                         f"with a nonzero maxsize so the blocking put "
                         f"is the backpressure, or record the upstream "
                         f"bound in the baseline")))
    return findings


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.select(SCOPE):
        findings.extend(_check_async_blocking(sf))
        findings.extend(_check_cross_thread(sf))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="cross-thread mutation without a lock; blocking calls in async; "
        "unbounded cross-thread queues",
    run=check,
    codes=("C201", "C202", "C203"),
)
