"""Lifecycle checker: paired-resource protocols, path-sensitively.

The repo has three acquire/release disciplines whose misuse is silent
HBM corruption, not an error: the paged KV pool's refcounts
(`BlockPool.alloc` → `unref`/`free`), the pinned radix handles
(`RadixIndex.lookup` → `RadixHit.release`, `plan_insert` →
`InsertPlan.commit`/`abort`), and bare `Lock.acquire` outside `with`.
PR 12's review found the shape this checker exists for: a path — an
exception path — between acquire and release that none of the flat
single-statement checkers could see. Built on analysis/dataflow.py:
every function that acquires is walked path-sensitively, and each
protocol comes from the declarative SPECS table below, so adding the
next paired resource is one tuple, not a new checker.

  L401  handle leaks on a normal path: acquired, then the function
        exits (return/fallthrough) on some path where it was neither
        released nor handed off
  L402  handle leaks on an EXCEPTION path — the PR-12 crash pattern: a
        raise between acquire and release unwinds past the pin
  L403  double release of a non-idempotent release (`commit` after
        commit/abort raises; `Lock.release` on an unlocked lock)
  L404  use after release: the handle is read after `release`/`abort`/
        `commit` resolved it (reading `plan.new_ids` after abort is
        reading freed block ids)

Ownership transfer ends tracking without a finding: returning the
handle, yielding it, storing it into an attribute/container, or
passing the handle itself to any non-release call (the scheduler hands
pinned hits to the engine; the engine releases them — each function is
checked for ITS span of the handle's life). Optional acquires
(`lookup`/`plan_insert` return None on miss) are tracked as
maybe-None; `if h is None` narrows per path, and a maybe-None leak is
reported with "may" phrasing at the same codes.

Pure stdlib, no JAX import — the CI gate runs before `pip install`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    SourceFile,
)
from symmetry_tpu.analysis.dataflow import (
    analyze,
    assigned_paths,
    dotted_path,
    iter_functions,
    walk_scope,
)

NAME = "lifecycle"

# Production code only: tests acquire handles to assert ON them (a
# fixture that deliberately leaks is the checker's own test data), and
# tools are one-shot processes whose exit releases everything.
GROUP = ("symmetry_tpu/*.py",)


@dataclass(frozen=True)
class ReleaseSpec:
    """One way to release a handle. mode "method": `h.m()` releases h.
    mode "arg": `anything.m(h)` releases h (the pool's `unref(ids)`
    shape, where the handle is the id list, not the receiver)."""

    methods: frozenset[str]
    mode: str = "method"
    idempotent: bool = True


@dataclass(frozen=True)
class ResourceSpec:
    """One paired-resource protocol, matched structurally by method
    name (no type inference): `kind` "result" tracks the acquire
    call's assigned result as the handle, "receiver" tracks the
    callee's receiver (`lock.acquire()` pins `lock` itself).
    `receiver_hint`, when set, requires the acquire receiver's last
    dotted segment to CONTAIN it (case-insensitive) — what keeps
    `pool.alloc` from matching every `.alloc` in sight."""

    name: str
    acquire: frozenset[str]
    releases: tuple[ReleaseSpec, ...]
    kind: str = "result"
    receiver_hint: str | None = None
    optional: bool = False          # acquire may return None (a miss)


SPECS: tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="radix-hit",
        acquire=frozenset({"lookup"}),
        optional=True,
        releases=(ReleaseSpec(frozenset({"release"}), idempotent=True),),
    ),
    ResourceSpec(
        name="insert-plan",
        acquire=frozenset({"plan_insert"}),
        optional=True,
        releases=(
            ReleaseSpec(frozenset({"commit"}), idempotent=False),
            ReleaseSpec(frozenset({"abort"}), idempotent=True),
        ),
    ),
    ResourceSpec(
        name="pool-blocks",
        acquire=frozenset({"alloc"}),
        receiver_hint="pool",
        optional=True,
        releases=(
            ReleaseSpec(frozenset({"unref", "free"}), mode="arg",
                        idempotent=False),
        ),
    ),
    ResourceSpec(
        name="lock",
        acquire=frozenset({"acquire"}),
        kind="receiver",
        receiver_hint="lock",
        releases=(ReleaseSpec(frozenset({"release"}), idempotent=False),),
    ),
    ResourceSpec(
        # The per-stream resume journal entry (provider/backends/base.py
        # ResumeJournal): track() on admission, release() on EVERY exit
        # path — exception edges included. A leaked entry is a finished
        # request the death path would stamp `emitted` for forever (and
        # an unbounded dict on a busy provider); an early release is a
        # crash shed that stamps 0 and costs the client its RNG-lane
        # anchor.
        name="resume-journal",
        acquire=frozenset({"track"}),
        receiver_hint="journal",
        releases=(ReleaseSpec(frozenset({"release"}), idempotent=True),),
    ),
    ResourceSpec(
        # The symledger cost account (engine/ledger.py): track() opens
        # a request's entry (None while tpu.ledger is off), finish()
        # builds its wire costs block, release() folds a handoff
        # without one — both idempotent, so every exit path may close
        # unconditionally. A leaked entry is a request whose device
        # seconds never fold into the aggregates: conservation silently
        # stops closing. The receiver hint keeps this spec off the
        # resume journal's same-named `track`.
        name="ledger-entry",
        acquire=frozenset({"track"}),
        receiver_hint="ledger",
        optional=True,
        releases=(ReleaseSpec(frozenset({"finish", "release"}),
                              idempotent=True),),
    ),
)

_ALL_ACQUIRES = frozenset().union(*(s.acquire for s in SPECS))

# Handle statuses. HELD: definitely pinned. OPT: pinned-or-None (an
# optional acquire nobody narrowed yet). RELEASED: resolved — further
# non-idempotent releases are L403, other reads L404.
_HELD, _OPT, _REL = "H", "O", "R"


@dataclass(frozen=True)
class _Handle:
    var: str            # dotted path holding the handle
    spec: int           # index into SPECS
    line: int           # acquire site (leak findings anchor here)
    status: str

    def at(self, status: str) -> "_Handle":
        return _Handle(self.var, self.spec, self.line, status)


# Abstract state: a sorted tuple of handles (hashable; the dataflow
# engine keeps distinct states distinct per path until they converge).
_State = tuple[_Handle, ...]


def _with(state: _State, *handles: _Handle) -> _State:
    keep = [h for h in state if all(h.var != n.var for n in handles)]
    return tuple(sorted(keep + list(handles),
                        key=lambda h: (h.var, h.line, h.spec)))


def _without(state: _State, *vars_: str) -> _State:
    return tuple(h for h in state if h.var not in vars_)


def _call_parts(call: ast.Call) -> tuple[str | None, str | None]:
    """(receiver dotted path, method name) of a call. A bare-name call
    (`lookup(ids)` through a bound-method variable) has no receiver
    but still a matchable trailing name."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return dotted_path(f.value), f.attr
    if isinstance(f, ast.Name):
        return None, f.id
    return None, None


def _maximal_paths(expr: ast.AST) -> list[str]:
    """Dotted paths of the MAXIMAL Name/Attribute chains in `expr`:
    `(hit, [t])` yields "hit" but `hit.length` yields only
    "hit.length" — returning a handle's attribute is a read of the
    handle, not a transfer of it."""
    out: list[str] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, (ast.Attribute, ast.Name)):
            p = dotted_path(n)
            if p is not None:
                out.append(p)
                return  # inner names are chain segments, not refs
        for c in ast.iter_child_nodes(n):
            visit(c)

    visit(expr)
    return out


def _receiver_ok(spec: ResourceSpec, recv: str | None) -> bool:
    if spec.receiver_hint is None:
        return True
    if recv is None:
        return False
    return spec.receiver_hint in recv.split(".")[-1].lower()


def _acquire_spec(call: ast.Call) -> int | None:
    recv, meth = _call_parts(call)
    if meth is None or meth not in _ALL_ACQUIRES:
        return None
    for i, spec in enumerate(SPECS):
        if meth in spec.acquire and _receiver_ok(spec, recv):
            return i
    return None


class _Semantics:
    """Dataflow semantics for one function. Findings are (code, line,
    var, acq_line, message) tuples; the checker maps them to Finding
    objects afterwards."""

    def __init__(self) -> None:
        # Per-CFG-node syntactic facts, computed once — the walker
        # re-enters transfer() for every abstract state that reaches a
        # node, and the AST scans depend only on the node.
        self._facts_cache: dict[int, tuple] = {}

    def initial(self) -> _State:
        return ()

    # ------------------------------------------------------------ facts

    def _facts(self, node, expr):
        """(calls, loads, yield_paths, walrus) for one CFG node.
        calls: (call, recv, meth, arg_paths, arg_ids) per same-scope
        Call; loads: (sub, path) per Name/Attribute Load; yield_paths:
        maximal paths yielded; walrus: (name, spec_i, lineno) per
        `(h := acquire())`. Nested def/lambda bodies are skipped —
        deferred code does not execute at this statement."""
        cached = self._facts_cache.get(id(node))
        if cached is not None:
            return cached
        calls: list[tuple] = []
        loads: list[tuple] = []
        yield_paths: set[str] = set()
        walrus: list[tuple] = []
        for sub in walk_scope(expr):
            if isinstance(sub, ast.Call):
                recv, meth = _call_parts(sub)
                args = list(sub.args) + [kw.value for kw in sub.keywords]
                calls.append((sub, recv, meth,
                              tuple(dotted_path(a) for a in args),
                              frozenset(id(a) for a in args)))
            elif isinstance(sub, (ast.Name, ast.Attribute)):
                if isinstance(getattr(sub, "ctx", None), ast.Load):
                    p = dotted_path(sub)
                    if p is not None:
                        loads.append((sub, p))
            elif isinstance(sub, (ast.Yield, ast.YieldFrom)):
                if sub.value is not None:
                    yield_paths.update(_maximal_paths(sub.value))
            elif isinstance(sub, ast.NamedExpr):
                if isinstance(sub.target, ast.Name) \
                        and isinstance(sub.value, ast.Call):
                    i = _acquire_spec(sub.value)
                    if i is not None:
                        walrus.append((sub.target.id, i,
                                       sub.value.func.lineno))
        facts = (calls, loads, frozenset(yield_paths), tuple(walrus))
        self._facts_cache[id(node)] = facts
        return facts

    # ------------------------------------------------------------ transfer

    def transfer(self, node, state: _State):
        stmt = node.stmt
        expr = node.expr if node.expr is not None else stmt
        if isinstance(stmt, ast.ExceptHandler):
            # The handler NODE is just the catch point — its body is
            # sequenced as separate nodes; walking it here would apply
            # every effect twice.
            expr = None
        findings: list[tuple] = []
        post = state
        releases: list[_Handle] = []

        calls, loads, yield_paths, walrus = self._facts(node, expr)

        # 1. Releases (before use-checking: the release call's own read
        #    of the handle is not a use-after-release).
        released_vars: set[str] = set()
        for call, recv, meth, arg_paths, _aids in calls:
            for h in post:
                spec = SPECS[h.spec]
                for rel in spec.releases:
                    if meth not in rel.methods:
                        continue
                    hit = (rel.mode == "method" and recv == h.var) or \
                          (rel.mode == "arg" and h.var in arg_paths)
                    if not hit:
                        continue
                    if h.status == _REL and not rel.idempotent:
                        findings.append((
                            "L403", call.func.lineno, h.var, h.line,
                            f"double release of {spec.name} handle "
                            f"`{h.var}` (acquired line {h.line}): "
                            f"`{meth}()` is not idempotent — on the "
                            f"path where it already resolved, this "
                            f"raises or double-frees"))
                    releases.append(h.at(_REL))
                    released_vars.add(h.var)
        if releases:
            post = _with(post, *releases)

        # 2. Use-after-release: a read INTO a released handle (its
        #    attributes — `plan.new_ids` after abort is freed block
        #    ids) or passing it onward to a call. A bare reference is
        #    NOT a use: `if hit is not None: hit.release()` in a
        #    cleanup handler reads the name, never the resource.
        arg_ids = frozenset().union(*(aids for *_rest, aids in calls)) \
            if calls else frozenset()
        for h in post:
            if h.status != _REL or h.var in released_vars:
                continue
            for sub, p in loads:
                deeper = p.startswith(h.var + ".")
                passed = p == h.var and id(sub) in arg_ids
                if deeper or passed:
                    spec = SPECS[h.spec]
                    findings.append((
                        "L404", sub.lineno, h.var, h.line,
                        f"use of {spec.name} handle `{h.var}` after "
                        f"release (acquired line {h.line}, resolved "
                        f"on this path) — its blocks may already be "
                        f"reused"))
                    break

        # 3. Ownership transfer: the handle ITSELF escapes — returned,
        #    yielded, stored into something, or passed to a call that
        #    is not one of its releases. Tracking ends, no finding.
        escaped: set[str] = set()
        held_vars = {h.var for h in post if h.status in (_HELD, _OPT)}
        if held_vars:
            # Only a MAXIMAL reference transfers ownership: `return
            # hit` escapes, `return hit.length` merely reads the pin
            # and must keep it tracked (and leaking).
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                escaped |= held_vars & set(_maximal_paths(stmt.value))
            escaped |= held_vars & yield_paths
            for _call, _recv, _meth, arg_paths, _aids in calls:
                for p in arg_paths:
                    if p in held_vars and p not in released_vars:
                        escaped.add(p)
            if isinstance(stmt, ast.Assign):
                # `self.hit = h` / `units[k] = (h, reqs)` hands
                # ownership off — the handle escapes even when packed
                # inside a tuple/list on the way into the container.
                # A plain local target transfers too: `pair = (hit, t)`
                # then `return pair` is ordinary code, and once the
                # handle lives under another name this intraprocedural
                # walk cannot follow it — alias, not a leak. (The
                # acquire statement itself never matches: its value's
                # maximal paths are the callee chain and arguments, not
                # the fresh handle.)
                escaped |= held_vars & set(_maximal_paths(stmt.value))
        if escaped:
            post = _without(post, *escaped)

        # 4. Rebinds: assigning over a variable drops its old handle.
        #    Overwriting a definitely-HELD handle is itself a leak.
        rebound = assigned_paths(stmt) if stmt is not None else set()
        acq: list[_Handle] = []
        # Only a LOCAL name binds a tracked handle: `self.hit =
        # idx.lookup(t)` stores ownership somewhere that outlives this
        # function — that is a transfer, not an acquisition to audit.
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            tgt = dotted_path(stmt.targets[0])
            if tgt is not None:
                spec_i, opt = self._acquire_of(stmt.value)
                if spec_i is not None:
                    status = _OPT if (opt or SPECS[spec_i].optional) \
                        else _HELD
                    acq.append(_Handle(tgt, spec_i, stmt.lineno, status))
        # Walrus acquires — `if (hit := idx.lookup(t)) is not None:` —
        # bind a tracked handle exactly like a plain assignment.
        for name, spec_i, lineno in walrus:
            status = _OPT if SPECS[spec_i].optional else _HELD
            acq.append(_Handle(name, spec_i, lineno, status))
        if rebound:
            acq_vars = {h.var for h in acq}
            for h in post:
                for rb in rebound:
                    if h.var != rb and not h.var.startswith(rb + "."):
                        continue
                    if h.status == _HELD and h.var not in acq_vars:
                        findings.append((
                            "L401", stmt.lineno, h.var, h.line,
                            f"{SPECS[h.spec].name} handle `{h.var}` "
                            f"(acquired line {h.line}) overwritten "
                            f"while still held — the pin leaks"))
            post = tuple(h for h in post
                         if not any(h.var == rb
                                    or h.var.startswith(rb + ".")
                                    for rb in rebound))

        # 5. Acquires. A result-kind acquire whose value is dropped
        #    (bare expression statement) leaks immediately.
        exc_base = _with(state, *releases) if releases else state
        if acq:
            post = _with(post, *acq)
        for call, recv, meth, _apaths, _aids in calls:
            spec_i = _acquire_spec(call)
            if spec_i is None:
                continue
            spec = SPECS[spec_i]
            if spec.kind == "receiver":
                if recv is not None:
                    h = _Handle(recv, spec_i, call.func.lineno, _HELD)
                    post = _with(post, h)
            elif not self._call_is_consumed(call, stmt):
                findings.append((
                    "L401", call.func.lineno, meth or "?",
                    call.func.lineno,
                    f"{spec.name} acquire result discarded — the "
                    f"pinned handle can never be released"))

        # Exception edge: the statement's effects may not have happened
        # (an acquire that raised acquired nothing), but releases
        # stick — a release that raises still released — and so do
        # escapes: arguments are evaluated before the call body runs,
        # so a callee that raises already received the handle and owns
        # its cleanup.
        if escaped:
            exc_base = _without(exc_base, *escaped)
        return post, exc_base, findings

    @staticmethod
    def _acquire_of(value: ast.AST) -> tuple[int | None, bool]:
        """(spec index, forced-optional) when `value` is an acquire
        call, possibly behind a conditional expression (`x if c else
        None` — the advance_chunked_prefill idiom)."""
        if isinstance(value, ast.Call):
            return _acquire_spec(value), False
        if isinstance(value, ast.IfExp):
            for arm in (value.body, value.orelse):
                if isinstance(arm, ast.Call):
                    i = _acquire_spec(arm)
                    if i is not None:
                        return i, True
        return None, False

    @staticmethod
    def _call_is_consumed(call: ast.Call, stmt) -> bool:
        """True when the acquire call's result is bound, returned, or
        otherwise fed into the surrounding expression — only a bare
        `idx.lookup(x)` statement discards the pin outright."""
        return not (isinstance(stmt, ast.Expr) and stmt.value is call)

    # ------------------------------------------------------------ branches

    def on_branch(self, test, state: _State, taken: bool):
        if test is None:
            return state
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            if not taken:
                return state  # any conjunct may have failed: no narrowing
            # All conjuncts held: narrow through each in turn (`ids is
            # None and self._evict_one()` — on the true branch, ids IS
            # None and its handle is gone before the eviction call runs).
            for part in test.values:
                state = self.on_branch(part, state, True)
                if state is None:
                    return None
            return state
        var, none_when_true = self._none_test(test)
        if var is None:
            return state
        for h in state:
            if h.var != var:
                continue
            is_none_branch = (taken == none_when_true)
            if h.status == _OPT:
                return _without(state, var) if is_none_branch \
                    else _with(state, h.at(_HELD))
            if h.status == _HELD and is_none_branch:
                return None  # held handles are not None: path infeasible
        return state

    @staticmethod
    def _none_test(test) -> tuple[str | None, bool]:
        """(var, none_when_true) for the narrowable shapes: `x is
        None`, `x is not None`, bare `x`, `not x`."""
        if isinstance(test, ast.NamedExpr):
            # `if (hit := idx.lookup(t)):` — the walrus target carries
            # the handle the branch narrows.
            test = test.target
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            left = test.left
            if isinstance(left, ast.NamedExpr):
                left = left.target
            var = dotted_path(left)
            if isinstance(test.ops[0], ast.Is):
                return var, True
            if isinstance(test.ops[0], ast.IsNot):
                return var, False
            return None, False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            var, nwt = _Semantics._none_test(test.operand)
            return (var, not nwt) if var is not None else (None, False)
        var = dotted_path(test)
        if var is not None:
            return var, False  # truthy handle == held
        return None, False

    # ---------------------------------------------------------------- exit

    def at_exit(self, state: _State, exceptional: bool):
        findings = []
        for h in state:
            if h.status == _REL:
                continue
            spec = SPECS[h.spec]
            code = "L402" if exceptional else "L401"
            maybe = "may leak" if h.status == _OPT else "leaks"
            how = ("an exception path unwinds past the pin"
                   if exceptional else "the function exits without "
                   "releasing it")
            rels = sorted(m for r in spec.releases for m in r.methods)
            findings.append((
                code, h.line, h.var, h.line,
                f"{spec.name} handle `{h.var}` (acquired line {h.line}) "
                f"{maybe}: {how} — call {' / '.join(rels)} on every "
                f"path, exception edges included"))
        return findings


def _function_acquires(func) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            _, meth = _call_parts(node)
            if meth in _ALL_ACQUIRES:
                return True
    return False


def _check_file(sf: SourceFile) -> Iterable[Finding]:
    for func in iter_functions(sf.tree):
        if not _function_acquires(func):
            continue
        sem = _Semantics()
        for code, line, var, acq_line, message in analyze(func, sem):
            del acq_line  # in the message; fingerprints stay line-free
            yield Finding(
                checker=NAME, code=code, path=sf.rel, line=line,
                symbol=f"{func.name}:{var}",
                message=f"{message} [in {func.name}()]")


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.select(GROUP):
        findings.extend(_check_file(sf))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="paired-resource lifecycle (pins/plans/locks) on every path",
    run=check,
    codes=("L401", "L402", "L403", "L404"),
)
