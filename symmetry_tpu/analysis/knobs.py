"""Config-knob checker: TpuConfig fields ↔ README knob reference.

Twelve PRs of knob growth left the `tpu:` config section documented by
scattered prose: a knob a reader cannot find might as well not exist,
and a documented knob that nothing reads is advice that silently does
nothing. This flat pass cross-references three sets — the `TpuConfig`
dataclass fields (the registry: `provider/config.py` rejects unknown
keys against it), the `tpu.<name>` mentions in README.md, and the
attribute/getattr read sites across `symmetry_tpu/` — and flags every
pairwise drift:

  K601  knob read by the engine/provider but never documented: no
        `tpu.<name>` mention anywhere in README.md
  K602  README documents a `tpu.<name>` that is not a TpuConfig field —
        stale docs (the config loader would reject the key)
  K603  TpuConfig field nothing reads — a dead knob (or a checker-
        invisible read; fix the idiom or prune the field)

A "read" is `X.field` / `getattr(X, "field", ...)` where X's dotted
receiver path has a segment containing "tpu" (`tpu_cfg.role`,
`config.tpu.mesh`, `self._tpu.decode_block`) — the idiom every knob
consumer in the repo uses. Sub-keys of dict-valued knobs
(`tpu.disagg.peer`) resolve to their first segment.

Pure stdlib, no JAX import — the CI gate runs before `pip install`.
"""

from __future__ import annotations

import ast
import os
import re

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    const_str,
    dotted_name,
)

NAME = "knobs"
GROUP = ("symmetry_tpu/*.py",)

# `tpu.<name>` not inside a longer dotted/word run — `symmetry_tpu.engine`
# is a module path, not a knob.
_DOC_RE = re.compile(r"(?<![\w.])tpu\.([a-z_][a-z_0-9]*)")


def _tpu_fields(project: Project) -> tuple[str, dict[str, int]]:
    """(defining file rel path, {field: line}) of the TpuConfig
    dataclass; empty when no scanned file defines it (fixture trees in
    tests stay self-contained)."""
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == "TpuConfig":
                fields = {s.target.id: s.lineno for s in node.body
                          if isinstance(s, ast.AnnAssign)
                          and isinstance(s.target, ast.Name)}
                return sf.rel, fields
    return "", {}


def _tpu_receiver(path: str | None) -> bool:
    return path is not None and any("tpu" in seg.lower()
                                    for seg in path.split("."))


def _read_sites(project: Project, fields: dict[str, int]) -> set[str]:
    reads: set[str] = set()
    for sf in project.select(GROUP):
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute) and node.attr in fields \
                    and _tpu_receiver(dotted_name(node.value)):
                reads.add(node.attr)
            elif isinstance(node, ast.Call) and len(node.args) >= 2 \
                    and dotted_name(node.func) == "getattr" \
                    and _tpu_receiver(dotted_name(node.args[0])):
                name = const_str(node.args[1])
                if name in fields:
                    reads.add(name)
    return reads


def check(project: Project) -> list[Finding]:
    reg_path, fields = _tpu_fields(project)
    readme = os.path.join(project.root, "README.md")
    if not fields or not os.path.exists(readme):
        return []
    with open(readme, encoding="utf-8") as fh:
        doc_lines = fh.read().splitlines()
    documented: dict[str, int] = {}
    for i, line in enumerate(doc_lines, 1):
        for m in _DOC_RE.finditer(line):
            documented.setdefault(m.group(1), i)
    reads = _read_sites(project, fields)

    findings: list[Finding] = []
    for f in sorted(reads - set(documented)):
        findings.append(Finding(
            checker=NAME, code="K601", path=reg_path, line=fields[f],
            symbol=f"tpu.{f}",
            message=f"knob `tpu.{f}` is read by the code but README.md "
                    f"never mentions it — document it in the knob "
                    f"reference"))
    for name in sorted(set(documented) - set(fields)):
        findings.append(Finding(
            checker=NAME, code="K602", path="README.md",
            line=documented[name], symbol=f"tpu.{name}",
            message=f"README documents `tpu.{name}` but TpuConfig has "
                    f"no such field — the config loader rejects it; "
                    f"fix or prune the doc"))
    for f in sorted(set(fields) - reads):
        findings.append(Finding(
            checker=NAME, code="K603", path=reg_path, line=fields[f],
            symbol=f"tpu.{f}",
            message=f"TpuConfig field `{f}` is never read anywhere in "
                    f"symmetry_tpu/ — a dead knob (or a read idiom this "
                    f"checker cannot see; use `<tpu receiver>.{f}`)"))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="tpu.* knobs: TpuConfig fields ↔ README docs ↔ read sites",
    run=check,
    codes=("K601", "K602", "K603"),
)
