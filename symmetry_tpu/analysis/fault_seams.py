"""Fault-seam checker: arming sites and guard sites must agree.

The fault-injection layer (utils/faults.py) is name-matched string
plumbing end to end: a seam armed as `host.pipe_write=crash` only does
anything because some call site guards `FAULTS.point("host.pipe_write")`.
Rename either side and nothing errors — the chaos test silently tests
nothing, which is worse than no test. This checker cross-references
the two sides:

  S401  a seam is ARMED somewhere (SYMMETRY_FAULTS env string, a
        provider-config `faults:` mapping, a `FAULTS.load(...)` call)
        but no `FAULTS.point/apoint` guard with that name exists in
        the package — the fault can never fire
  S402  a guard site exists in the package but nothing in the repo
        ever arms that seam — the recovery path behind it is untested

Arming extraction understands the three real shapes:

  - `FAULTS.load("seam=action@trigger;seam2=…")` env-grammar strings
  - `FAULTS.load({"seam": "action"})` mapping literals
  - `{"faults": {"seam": "action"}}` entries inside any config dict
    literal (the provider-yaml shape tests/tools build inline)
  - string literals that fully parse under the SYMMETRY_FAULTS grammar
    (catches specs routed through env dicts / subprocess plumbing)

A file that arms a seam AND contains its own guard/fire call for that
name is self-contained (the injector's own unit tests) and exempt from
S401 — it is exercising the mechanism, not a production seam.
"""

from __future__ import annotations

import ast
import re

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    SourceFile,
    call_name,
    const_str,
)

NAME = "fault-seam"

# Guard sites live in production code (package + the smoke drivers'
# protocol-faithful stand-ins).
GUARD_SCOPE = ("symmetry_tpu/**", "tools/*.py", "tests/fake_host.py")
# Arming happens anywhere: tests, tools, package defaults.
ARM_SCOPE = ("symmetry_tpu/**", "tools/*.py", "tests/**")

_GUARD_METHODS = {"point", "apoint"}
_FIRE_METHODS = {"point", "apoint", "fire"}

# One `seam=action[@trigger]` entry of the SYMMETRY_FAULTS grammar. The
# seam shape is pinned to dotted lower_snake names (one or more dots —
# `disagg.net.drop_link` is three segments) so ordinary `key=value`
# strings elsewhere in the repo can never parse as specs.
_SPEC_ENTRY = re.compile(
    r"^(?P<seam>[a-z_][a-z0-9_]*(?:\.[a-z_][a-z0-9_]*)+)="
    r"(?P<action>crash|hang|delay|error|drop_frame)"
    r"(?:\([^)]*\))?(?:@[a-z=0-9_.]+)?$")


def _parse_spec_string(s: str) -> set[str]:
    """Seam names from an env-grammar string; empty set when the string
    is not entirely spec-shaped."""
    entries = [e.strip() for e in s.split(";") if e.strip()]
    if not entries:
        return set()
    seams: set[str] = set()
    for e in entries:
        m = _SPEC_ENTRY.match(e)
        if m is None:
            return set()
        seams.add(m.group("seam"))
    return seams


def _seams_from_dict(node: ast.Dict) -> set[str]:
    """Seam names when a dict literal is fault-mapping-shaped: every
    key a dotted seam string, every value a parseable action spec (or
    list thereof)."""
    if not node.keys:
        return set()
    seams: set[str] = set()
    for k, v in zip(node.keys, node.values):
        key = const_str(k)
        if key is None or "." not in key:
            return set()
        vals = (v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v])
        if not vals:
            return set()
        for one in vals:
            spec = const_str(one)
            if spec is None or not _parse_spec_string(f"{key}={spec}"):
                return set()
        seams.add(key)
    return seams


def _local_injector_arg(node: ast.AST) -> bool:
    """Is this node an argument of `<local>.load(...)` / `parse_rule(...)`
    on something that is NOT the process-global FAULTS? Those arm a
    throwaway injector instance (the injector's own unit tests), not a
    production seam."""
    parent = getattr(node, "sym_parent", None)
    while parent is not None and not isinstance(parent, ast.Call):
        if isinstance(parent, (ast.stmt, ast.Module)):
            return False
        parent = getattr(parent, "sym_parent", None)
    if not isinstance(parent, ast.Call):
        return False
    cn = call_name(parent)
    if cn is None:
        return False
    leaf = cn.split(".")[-1]
    if leaf == "parse_rule":
        return True
    if leaf == "load" and not cn.endswith("FAULTS.load"):
        return True
    return False


def _collect_armed(sf: SourceFile) -> dict[str, int]:
    """seam -> first arming line in one file."""
    armed: dict[str, int] = {}

    def note(seams: set[str], line: int) -> None:
        for s in seams:
            armed.setdefault(s, line)

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn is not None and cn.endswith("FAULTS.load") and node.args:
                arg = node.args[0]
                s = const_str(arg)
                if s is not None:
                    note(_parse_spec_string(s), node.lineno)
                elif isinstance(arg, ast.Dict):
                    note(_seams_from_dict(arg), node.lineno)
        elif isinstance(node, ast.Dict):
            if _local_injector_arg(node):
                continue
            # A fault-mapping-shaped dict literal arms its seams
            # whether it sits under a "faults" config key or travels
            # through a variable first — the dotted-seam-key +
            # action-grammar-value shape is distinctive enough that
            # nothing else in the repo parses as one.
            note(_seams_from_dict(node), node.lineno)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # Bare spec strings (env plumbing): only full-grammar
            # matches count, so prose never does; strings feeding a
            # local injector instance are the parser's own tests.
            if ("=" in node.value and "." in node.value
                    and not _local_injector_arg(node)):
                note(_parse_spec_string(node.value), node.lineno)
    return armed


def _collect_guards(sf: SourceFile, methods: set[str],
                    any_receiver: bool = False) -> dict[str, int]:
    """seam -> first guard line for FAULTS.<method>("seam") calls.
    `any_receiver=True` also accepts local injector instances
    (`inj.point(...)`) — used only for the self-containment check,
    never to satisfy a production guard."""
    guards: dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        if cn is None or cn.split(".")[-1] not in methods:
            continue
        head = cn.rsplit(".", 1)[0].split(".")[-1]
        if head != "FAULTS" and not (any_receiver and head):
            continue
        if node.args:
            seam = const_str(node.args[0])
            if seam is not None:
                guards.setdefault(seam, node.lineno)
    return guards


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []

    guard_files = project.select(GUARD_SCOPE)
    arm_files = project.select(ARM_SCOPE)

    guards: dict[str, tuple[str, int]] = {}
    for sf in guard_files:
        for seam, line in _collect_guards(sf, _GUARD_METHODS).items():
            guards.setdefault(seam, (sf.rel, line))

    armed: dict[str, tuple[str, int]] = {}
    self_contained: set[str] = set()
    for sf in arm_files:
        file_armed = _collect_armed(sf)
        if not file_armed:
            continue
        # Self-contained file: arms AND fires the same seam itself
        # (injector unit tests) — those seams are not production seams.
        own_fires = _collect_guards(sf, _FIRE_METHODS, any_receiver=True)
        for seam, line in file_armed.items():
            if seam in own_fires:
                self_contained.add(seam)
            armed.setdefault(seam, (sf.rel, line))

    for seam, (rel, line) in sorted(armed.items()):
        if seam in guards or seam in self_contained:
            continue
        findings.append(Finding(
            checker=NAME, code="S401", path=rel, line=line, symbol=seam,
            message=(f'seam "{seam}" is armed here but no '
                     f'FAULTS.point/apoint guard with that name exists '
                     f'in the package — the fault can never fire')))
    for seam, (rel, line) in sorted(guards.items()):
        if seam in armed:
            continue
        findings.append(Finding(
            checker=NAME, code="S402", path=rel, line=line, symbol=seam,
            message=(f'seam "{seam}" is guarded here but nothing in '
                     f'tests/tools/configs ever arms it — the recovery '
                     f'path behind it is untested')))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="SYMMETRY_FAULTS arming sites ↔ FAULTS.point guard sites",
    run=check,
    codes=("S401", "S402"),
)
