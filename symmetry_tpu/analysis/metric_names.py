"""Metric-name checker: registry ↔ emission-site agreement.

Metric names are string-matched at scrape time, the same failure shape
as a wire op: a typo'd name does not error — it creates a second,
forever-empty family, and the dashboard panel (or the SLO monitor, or
symtop's column) quietly reads zeros. The `MetricName` registry in
utils/metrics.py is the one place names live; this checker makes the
agreement static:

  M101  raw metric-name string literal at an emission site where a
        `MetricName` constant exists — emitters must go through the
        registry, which is what kills `sym_provider_requests_total` vs
        `sym_provider_request_total` spelling drift
  M102  name emitted (a `METRICS.counter/gauge/histogram(...)` call)
        but not registered in `MetricName` at all — including a
        reference to a nonexistent attribute (`MetricName.TYPO`), which
        is an AttributeError waiting on first emission
  M103  name registered in `MetricName` but never emitted anywhere in
        the scanned group — dead registry weight, or (worse) the
        emitter was renamed away from it and some consumer still
        queries the old name

Emission extraction: calls whose callee is `<...>.METRICS.counter`,
`.gauge`, or `.histogram` with a resolvable first argument (string
constant or `MetricName.X`). Handles created through the module-global
`METRICS` are the project idiom (registration IS the emission site the
checker pins — the returned handle's `.inc()/.observe()` calls carry no
name). Tests are deliberately outside the group: they pin names as raw
literals on purpose, independent of the constants.
"""

from __future__ import annotations

import ast

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    const_str,
    dotted_name,
)

NAME = "metric-names"

# Every production emitter: the whole package (fnmatch `*` crosses
# path separators). tools/ and tests/ stay out — tools only PARSE
# exposition text, and tests pin names as deliberate raw literals.
EMIT_GROUP = ("symmetry_tpu/*.py",)

_REGISTRY_CLASS = "MetricName"
_EMIT_METHODS = {"counter", "gauge", "histogram"}
_RECEIVER = "METRICS"


def _registry_lines(project: Project) -> dict[str, tuple[str, int]]:
    """attr value -> (file, line) for the MetricName class body — M103
    findings anchor at the registered-but-dead assignment itself."""
    out: dict[str, tuple[str, int]] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == _REGISTRY_CLASS:
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)):
                        val = const_str(stmt.value)
                        if val is not None:
                            out[val] = (sf.rel, stmt.lineno)
                return out
    return out


def check(project: Project) -> list[Finding]:
    registry = project.class_constants(_REGISTRY_CLASS)
    if not registry:
        return []  # fixture tree without the registry — nothing to pin
    values = set(registry.values())
    by_value = {v: k for k, v in registry.items()}
    findings: list[Finding] = []
    emitted: set[str] = set()

    for sf in project.select(EMIT_GROUP):
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_METHODS and node.args):
                continue
            recv = dotted_name(node.func.value)
            if recv is None or recv.split(".")[-1] != _RECEIVER:
                continue
            arg = node.args[0]
            raw = const_str(arg)
            if raw is not None:
                emitted.add(raw)
                if raw in values:
                    findings.append(Finding(
                        checker=NAME, code="M101", path=sf.rel,
                        line=arg.lineno, symbol=raw,
                        message=f'raw metric-name literal "{raw}" — use '
                                f'MetricName.{by_value[raw]} from '
                                f'symmetry_tpu/utils/metrics.py'))
                else:
                    findings.append(Finding(
                        checker=NAME, code="M102", path=sf.rel,
                        line=arg.lineno, symbol=raw,
                        message=f'metric "{raw}" is emitted here but not '
                                f'registered in MetricName — a typo makes '
                                f'a silently-empty family, register it'))
                continue
            dn = dotted_name(arg)
            if dn is None:
                continue  # computed name (registry internals) — unscoped
            head, _, attr = dn.rpartition(".")
            if head.split(".")[-1] != _REGISTRY_CLASS:
                continue
            if attr in registry:
                emitted.add(registry[attr])
            else:
                findings.append(Finding(
                    checker=NAME, code="M102", path=sf.rel,
                    line=arg.lineno, symbol=dn,
                    message=f'{dn} does not exist in the MetricName '
                            f'registry — AttributeError on first '
                            f'emission'))

    lines = _registry_lines(project)
    for value in sorted(values - emitted):
        rel, lineno = lines.get(value, ("symmetry_tpu/utils/metrics.py", 1))
        findings.append(Finding(
            checker=NAME, code="M103", path=rel, line=lineno,
            symbol=value,
            message=f'metric "{value}" is registered in MetricName but '
                    f'never emitted — dead registry entry or a renamed '
                    f'emitter left consumers querying an empty family'))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="MetricName registry / emission-site agreement",
    run=check,
    codes=("M101", "M102", "M103"),
)
