"""Shared infrastructure for the symlint checker suite.

The project's correctness invariants — wire-protocol agreement between
the engine host and the provider backend, lock discipline across the
threaded engine tier, compile-cache stability inside jit-traced
functions, fault-seam name agreement between guards and arming sites —
were all enforced at runtime (a drifted op name hangs a stream; a
missed lock loses a counter increment; a data-dependent branch
recompiles mid-traffic). Each checker in this package makes one of
those invariants *static*: an AST pass over the repo that fails CI on
drift instead of waiting for it to surface under load.

This module holds what every checker shares:

  - `SourceFile`: one parsed file (path, source, AST with parent links)
  - `Finding`: one diagnostic, with a line-number-free `fingerprint`
    so baseline suppressions survive unrelated edits
  - `Baseline`: the suppression file (JSON, one justified entry per
    intentionally-accepted finding)
  - `Project`: the scanned file set plus the cross-file helpers
    (glob-scoped file selection, constant-registry extraction)
  - small AST helpers (`const_str`, `call_name`, `attach_parents`)

Checkers are cross-file by design (the wire-contract checker needs
producer AND consumer sets), so each one receives the whole `Project`
and returns a list of `Finding`s — there is no per-file visitor
contract to fight when an invariant spans processes.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

# Directories never worth parsing (build junk, caches, vendored code —
# a repo-local virtualenv holds thousands of third-party files no
# checker scopes, but walking them would turn the seconds-long gate
# into minutes).
_SKIP_DIRS = {".git", "__pycache__", ".ruff_cache", "build", "dist",
              ".eggs", "node_modules", ".claude", ".venv", "venv",
              ".tox", ".mypy_cache", "site-packages", ".pytest_cache"}


# --------------------------------------------------------------- findings


@dataclass
class Finding:
    """One diagnostic from one checker.

    `symbol` is the stable identity of WHAT drifted (an op name, a seam
    name, a `Class.attr`), not where: the fingerprint is built from it
    so a baseline entry keeps matching when unrelated edits move the
    line. Sort/compare order is file order, which is what both output
    modes print."""

    checker: str          # e.g. "wire-contract"
    code: str             # e.g. "W102"
    path: str             # repo-relative, "/" separated
    line: int
    message: str
    symbol: str = ""      # stable subject (op/seam/attr name)
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol or self.message}"

    def to_dict(self) -> dict[str, Any]:
        return {"checker": self.checker, "code": self.code,
                "path": self.path, "line": self.line,
                "message": self.message, "symbol": self.symbol,
                "fingerprint": self.fingerprint,
                "baselined": self.baselined}

    def render(self) -> str:
        tag = " (baselined)" if self.baselined else ""
        return (f"{self.path}:{self.line}: {self.code} "
                f"[{self.checker}] {self.message}{tag}")


class Baseline:
    """The suppression file: a JSON list of justified fingerprints.

    Shape (reasons are mandatory — an unexplained suppression is just
    drift with a paper trail):

        {"version": 1,
         "suppressions": [
            {"fingerprint": "C202:path.py:Cls._attr", "reason": "..."}]}

    `match()` marks a finding baselined; `unused()` reports entries
    that matched nothing this run, so stale suppressions surface
    instead of silently shadowing future regressions."""

    def __init__(self, entries: list[dict[str, str]] | None = None) -> None:
        self.entries = entries or []
        self._by_fp = {e["fingerprint"]: e for e in self.entries
                       if isinstance(e, dict) and "fingerprint" in e}
        self._hit: set[str] = set()

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        entries = data.get("suppressions", [])
        if not isinstance(entries, list):
            raise ValueError(f"{path}: 'suppressions' must be a list")
        for e in entries:
            if not isinstance(e, dict) or not e.get("fingerprint"):
                raise ValueError(f"{path}: bad suppression entry {e!r}")
            if not e.get("reason"):
                raise ValueError(
                    f"{path}: suppression {e['fingerprint']!r} has no "
                    f"reason — justify it or fix the finding")
        return cls(entries)

    def match(self, finding: Finding) -> bool:
        if finding.fingerprint in self._by_fp:
            self._hit.add(finding.fingerprint)
            return True
        return False

    def unused(self) -> list[str]:
        return [fp for fp in self._by_fp if fp not in self._hit]


# ------------------------------------------------------------ source files


@dataclass
class SourceFile:
    """One parsed file. `tree` is None when the file does not parse —
    checkers skip it (a syntax error is the byte-compile step's job,
    not ours)."""

    path: str             # absolute
    rel: str              # repo-relative, "/" separated
    source: str
    tree: ast.Module | None = None

    def matches(self, patterns: Iterable[str]) -> bool:
        return any(fnmatch.fnmatch(self.rel, p) for p in patterns)


def attach_parents(tree: ast.AST) -> None:
    """Annotate every node with `.sym_parent` — several checkers need
    to know the context an expression sits in (dict value vs compare
    operand, call func vs argument)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.sym_parent = node  # type: ignore[attr-defined]


def parse_source(path: str, rel: str, source: str) -> SourceFile:
    sf = SourceFile(path=path, rel=rel, source=source)
    try:
        sf.tree = ast.parse(source, filename=rel)
    except SyntaxError:
        sf.tree = None
    else:
        attach_parents(sf.tree)
    return sf


def load_file(root: str, rel: str) -> SourceFile:
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    return parse_source(path, rel.replace(os.sep, "/"), source)


def iter_py_files(root: str) -> list[str]:
    """Repo-relative paths of every .py file under `root`, skipping
    VCS/build directories. Sorted for deterministic output."""
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for name in sorted(filenames):
            if name.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, name), root)
                out.append(rel.replace(os.sep, "/"))
    return out


# ----------------------------------------------------------- AST helpers


def const_str(node: ast.AST | None) -> str | None:
    """The value of a string-constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def dotted_name(node: ast.AST) -> str | None:
    """`a.b.c` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's callee (`time.sleep`, `FAULTS.point`)."""
    return dotted_name(node.func)


# --------------------------------------------------------------- project


class Project:
    """The scanned file set plus shared cross-file lookups."""

    def __init__(self, root: str, files: list[SourceFile]) -> None:
        self.root = root
        self.files = files

    @classmethod
    def scan(cls, root: str, rels: list[str] | None = None) -> "Project":
        rels = rels if rels is not None else iter_py_files(root)
        return cls(root, [load_file(root, r) for r in rels])

    def select(self, patterns: Iterable[str]) -> list[SourceFile]:
        pats = list(patterns)
        return [f for f in self.files if f.tree is not None
                and f.matches(pats)]

    def class_constants(self, class_name: str) -> dict[str, str]:
        """`NAME -> "value"` for a module-level class of string
        constants (the HostOp / MessageKey registries in
        protocol/keys.py). Empty when no scanned file defines it —
        checkers then skip registry-dependent rules, which keeps
        fixture trees in tests self-contained."""
        for sf in self.files:
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if (isinstance(node, ast.ClassDef)
                        and node.name == class_name):
                    out: dict[str, str] = {}
                    for stmt in node.body:
                        if (isinstance(stmt, ast.Assign)
                                and len(stmt.targets) == 1
                                and isinstance(stmt.targets[0], ast.Name)):
                            val = const_str(stmt.value)
                            if val is not None:
                                out[stmt.targets[0].id] = val
                    return out
        return {}


# ---------------------------------------------------------------- runner


@dataclass
class CheckerSpec:
    name: str
    doc: str
    run: Callable[[Project], list[Finding]]
    codes: tuple[str, ...] = field(default_factory=tuple)


def run_suite(project: Project, checkers: Iterable[CheckerSpec],
              baseline: Baseline | None = None) -> list[Finding]:
    """Run every checker, mark baselined findings, return file order."""
    findings: list[Finding] = []
    for spec in checkers:
        findings.extend(spec.run(project))
    if baseline is not None:
        for f in findings:
            f.baselined = baseline.match(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return findings
