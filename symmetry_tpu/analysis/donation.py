"""Donation checker: donated jit buffers must not be read again.

`jax.jit(..., donate_argnums=...)` lets XLA reuse an input buffer for
the output — the engine's whole steady-state decode loop depends on it
(the KV cache would otherwise double in HBM every step). The failure
mode is silent: on TPU a donated array is not poisoned, it ALIASES the
output, so reading it after the call returns whatever the kernel wrote
there — garbage tokens, not an exception. (CPU jax warns; the chip
does not.) The discipline is purely syntactic — every donated argument
must be REBOUND from the call's return before the next read — so it is
statically checkable, and path-sensitively so: the bug is reading the
stale name on one path (a retry arm, an exception handler) while the
happy path rebinds it.

Resolution: donation sites are found per file — `self._f = jax.jit(fn,
donate_argnums=(k,))` (the engine's build() idiom, including the
mesh/no-mesh double registration) and `@functools.partial(jax.jit,
donate_argnums=(k,))` decorators. Every call through the registered
name is then walked with the dataflow engine:

  D501  a donated argument is read after the jitted call on some path
        without being rebound — use-after-donation aliasing
  D502  the jitted call's result is discarded (bare expression
        statement): the donated buffer was invalidated and the only
        copy of its replacement dropped

Rebinding any prefix clears the poison (`job.cache = None` poisons
nothing and clears `job.cache`; `self.state = self._decode(...,
self.state, ...)` in one statement is the idiom and never flags).

Pure stdlib, no JAX import — the CI gate runs before `pip install`.
"""

from __future__ import annotations

import ast
from typing import Iterable

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    SourceFile,
)
from symmetry_tpu.analysis.dataflow import (
    analyze,
    dotted_path,
    iter_functions,
    walk_scope,
)

NAME = "donation"

# Wherever jits are built: the engine package and ops/models (decorator
# style). Tests/tools don't donate.
GROUP = ("symmetry_tpu/*.py",)


def _donate_positions(call: ast.Call) -> tuple[int, ...] | None:
    """The constant donate_argnums of a jax.jit(...) call, else None
    (no donation, or not resolvable statically)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None
                out.append(e.value)
            return tuple(out)
        return None
    return None


def _is_jit(func: ast.AST) -> bool:
    p = dotted_path(func)
    return p is not None and p.split(".")[-1] == "jit"


def donation_registry(tree: ast.Module) -> dict[str, tuple[int, ...]]:
    """Donated-callable names for one file: dotted assignment target of
    `<t> = jax.jit(fn, donate_argnums=...)`, or the name of a def
    decorated `@partial(jax.jit, donate_argnums=...)`. Re-registration
    (the engine's mesh/no-mesh arms) unions positions — conservative
    either way."""
    reg: dict[str, tuple[int, ...]] = {}

    def add(name: str, pos: tuple[int, ...]) -> None:
        reg[name] = tuple(sorted(set(reg.get(name, ()) + pos)))

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if _is_jit(call.func):
                pos = _donate_positions(call)
                if pos:
                    for t in node.targets:
                        p = dotted_path(t)
                        if p is not None:
                            add(p, pos)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                head = dotted_path(dec.func)
                if head is None or head.split(".")[-1] != "partial":
                    continue
                if not (dec.args and _is_jit(dec.args[0])):
                    continue
                pos = _donate_positions(dec)
                if pos:
                    add(node.name, pos)
    return reg


# Abstract state: sorted tuple of (poisoned path, donation line).
_State = tuple[tuple[str, int], ...]


class _Semantics:
    def __init__(self, registry: dict[str, tuple[int, ...]]) -> None:
        self.registry = registry

    def initial(self) -> _State:
        return ()

    def _donated_call(self, call: ast.Call) -> tuple[int, ...] | None:
        p = dotted_path(call.func)
        if p is None:
            return None
        return self.registry.get(p)

    def transfer(self, node, state: _State):
        stmt = node.stmt
        expr = node.expr if node.expr is not None else stmt
        if isinstance(stmt, ast.ExceptHandler):
            expr = None  # body statements are their own nodes
        findings: list[tuple] = []
        post = list(state)

        # walk_scope, not ast.walk: a lambda/nested-def body is deferred
        # code — it does not execute (or read anything) at this
        # statement, and by the time a scheduled callback runs the happy
        # path has usually rebound the name.
        calls = [n for n in walk_scope(expr) if isinstance(n, ast.Call)] \
            if expr is not None else []
        donated_here: list[tuple[str, ast.Call]] = []
        for call in calls:
            pos = self._donated_call(call)
            if pos is None:
                continue
            for k in pos:
                if k < len(call.args) and not isinstance(
                        call.args[k], ast.Starred):
                    p = dotted_path(call.args[k])
                    if p is not None:
                        donated_here.append((p, call))
            if isinstance(stmt, ast.Expr) and stmt.value is call:
                findings.append((
                    "D502", call.func.lineno,
                    dotted_path(call.func) or "?",
                    f"result of donated-jit call "
                    f"`{dotted_path(call.func)}` discarded — the "
                    f"donated buffer was invalidated and its "
                    f"replacement dropped; bind the return value"))

        # 1. Reads of already-poisoned paths (the donated args read BY
        #    this statement's own call were read before dispatch — they
        #    are poisoned only AFTER; same-statement reads are fine).
        if expr is not None and post:
            for sub in walk_scope(expr):
                if not isinstance(sub, (ast.Name, ast.Attribute)):
                    continue
                if not isinstance(getattr(sub, "ctx", None), ast.Load):
                    continue
                p = dotted_path(sub)
                if p is None:
                    continue
                for path, dline in post:
                    if p == path or p.startswith(path + "."):
                        findings.append((
                            "D501", sub.lineno, path,
                            f"`{p}` read here, but `{path}` was donated "
                            f"to a jitted call on line {dline} and never "
                            f"rebound — on TPU it aliases the call's "
                            f"OUTPUT buffer now (silent garbage, not an "
                            f"error)"))
                        break

        # 1b. An augmented assignment's target is an implicit LOAD the
        #     ctx-based scan above cannot see (`self.state += d` reads
        #     the donated buffer to compute the new value) — flag it
        #     before step 2 clears the poison for the store half.
        if isinstance(stmt, ast.AugAssign) and post:
            p = dotted_path(stmt.target)
            if p is not None:
                for path, dline in post:
                    if p == path or p.startswith(path + "."):
                        findings.append((
                            "D501", stmt.lineno, path,
                            f"`{p}` augmented-assigned here, but `{path}` "
                            f"was donated to a jitted call on line {dline} "
                            f"and never rebound — the read half aliases "
                            f"the call's OUTPUT buffer (silent garbage, "
                            f"not an error)"))
                        break

        # 2. Rebinds clear poison — assigning a path clears it and
        #    everything under it; assigning `job` clears `job.cache`.
        targets: list[str] = []
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                targets.extend(_target_paths(t))
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets.extend(_target_paths(stmt.target))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)) \
                and node.expr is getattr(stmt, "iter", None):
            targets.extend(_target_paths(stmt.target))
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                targets.extend(_target_paths(t))
        if targets:
            # Assigning a path (or a prefix of it) clears the poison;
            # assigning INTO the donated object (`job.cache.k = v`) is
            # itself a read of the stale buffer and stays poisoned —
            # step 1 already flagged the implicit load.
            post = [(p, ln) for p, ln in post
                    if not any(p == t or p.startswith(t + ".")
                               for t in targets)]

        # 3. This statement's donations take effect AFTER its reads —
        #    unless the same statement rebinds the path (the
        #    `state = f(state)` idiom).
        for p, call in donated_here:
            if any(p == t or t.startswith(p + ".")
                   or p.startswith(t + ".") for t in targets):
                continue
            if all(p != q for q, _ in post):
                post.append((p, call.func.lineno))

        post_t = tuple(sorted(post))
        # Donation happens at dispatch: poison survives the exception
        # edge too (the call raised AFTER invalidating the buffer is
        # the conservative read).
        return post_t, post_t, findings

    def on_branch(self, test, state: _State, taken: bool):
        return state

    def at_exit(self, state: _State, exceptional: bool):
        return ()  # a poisoned local dying at exit is fine


def _target_paths(t: ast.AST) -> list[str]:
    if isinstance(t, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in t.elts:
            out.extend(_target_paths(e))
        return out
    if isinstance(t, ast.Starred):
        return _target_paths(t.value)
    p = dotted_path(t)
    return [p] if p is not None else []


def _check_file(sf: SourceFile) -> Iterable[Finding]:
    registry = donation_registry(sf.tree)
    if not registry:
        return
    names = {n.split(".")[-1] for n in registry}
    for func in iter_functions(sf.tree):
        if not any(isinstance(n, ast.Call)
                   and (dp := dotted_path(n.func)) is not None
                   and dp.split(".")[-1] in names
                   for n in ast.walk(func)):
            continue
        sem = _Semantics(registry)
        for code, line, symbol, message in analyze(func, sem):
            yield Finding(
                checker=NAME, code=code, path=sf.rel, line=line,
                symbol=f"{func.name}:{symbol}",
                message=f"{message} [in {func.name}()]")


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.select(GROUP):
        findings.extend(_check_file(sf))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="donate_argnums buffers never read after the jitted call",
    run=check,
    codes=("D501", "D502"),
)
