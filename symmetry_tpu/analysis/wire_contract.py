"""Wire-contract checker: host-pipe ops and MessageKey vocabulary.

The engine host and the provider backend speak a hand-rolled JSON-lines
protocol (`{"op": ...}` frames, engine/host.py docstring is the spec);
the provider/client/server tier speaks `MessageKey` frames. Both are
string-matched at runtime, so a renamed or misspelled op does not
error — the frame is silently ignored and the stream hangs until a
watchdog fires. This checker makes the contract static:

  W101  raw op string literal where a `HostOp` constant exists
        (producers and consumers must go through protocol/keys.py —
        the centralization that kills `"op": "adopt"` vs `"op":"adopt"`
        spelling drift)
  W102  op produced (a `{"op": X}` frame is built) but no consumer in
        the scanned group ever dispatches on it
  W103  op consumed (an `op == X` / `.get("op") == X` dispatch exists)
        but nothing in the group ever produces it
  W104  op name not registered in `HostOp` at all
  W105  raw string literal used where a `MessageKey` constant exists
        (`msg.key == "ping"`, `peer.send("pong", ...)`)
  W106  MessageKey sent somewhere but handled nowhere in the tier
  W107  MessageKey handled somewhere but sent nowhere in the tier

The handoff-LINK protocol (engine/disagg/net.py envelope headers,
`LinkOp` registry) is checked with the same W101–W104 semantics over
its own group (LINK_GROUP): the link ops deliberately reuse some HostOp
value strings (a link `submit` forwards a host `submit`), so each
registry is resolved only against its own `<Class>.<ATTR>` references —
a `LinkOp.X` attribute is invisible to the HostOp scan and vice versa,
and raw literals are flagged against whichever registry owns the group.

Producer extraction: any dict literal with an `"op"` key (string
constant or `HostOp.X`). Consumer extraction: comparisons and
membership tests where one side is an op constant and the other is an
op-shaped expression (a name/attribute ending in `op`, or a
`.get("op")` call). Cross-checking runs over the whole scanned group at
once, so moving a producer without its consumer — the exact drift that
bit the adopt path — fails CI instead of hanging a stream.

Keys that are deliberately one-sided (e.g. emitted for an external
consumer) belong in the baseline file with a reason, not out of the
scan scope.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from symmetry_tpu.analysis.core import (
    CheckerSpec,
    Finding,
    Project,
    SourceFile,
    const_str,
    dotted_name,
)

NAME = "wire-contract"

# The host-pipe protocol group: every file that builds or dispatches on
# `{"op": ...}` frames. tests/fake_host.py is included on purpose — it
# is the protocol-faithful stand-in the chaos suite trusts, so it must
# drift WITH the real host, not away from it.
OP_GROUP = (
    "symmetry_tpu/engine/host.py",
    "symmetry_tpu/engine/disagg/*.py",
    "symmetry_tpu/provider/backends/*.py",
    "tools/*.py",
    "tests/fake_host.py",
)

# The handoff-link protocol group (LinkOp registry): both endpoints of
# the cross-machine link live here; anything that grows a new link-op
# producer or consumer belongs in this set.
LINK_GROUP = (
    "symmetry_tpu/engine/disagg/net.py",
    "symmetry_tpu/engine/disagg/node.py",
)

# The MessageKey tier: everything that sends or handles peer frames.
KEY_GROUP = (
    "symmetry_tpu/provider/*.py",
    "symmetry_tpu/provider/backends/*.py",
    "symmetry_tpu/client/*.py",
    "symmetry_tpu/server/*.py",
    "symmetry_tpu/network/*.py",
)

# `.send(key, ...)`-shaped producer methods and `.key` consumer
# attribute for the MessageKey tier.
_SEND_METHODS = {"send"}

_OP_REGISTRY_CLASS = "HostOp"
_LINK_REGISTRY_CLASS = "LinkOp"
_KEY_REGISTRY_CLASS = "MessageKey"


@dataclass
class _OpUse:
    value: str
    line: int
    raw: bool             # spelled as a string literal (not a constant)
    file: SourceFile = field(repr=False, default=None)  # type: ignore


def _op_value(node: ast.AST, registry: dict[str, str],
              registry_class: str,
              missing: list | None = None) -> tuple[str | None, bool]:
    """Resolve an op-valued expression: a string constant (raw=True) or
    a `HostOp.X` attribute (raw=False). (None, False) when neither.

    A reference to a registry attribute that does NOT exist
    (`HostOp.EVNT`) is exactly the typo class this checker exists for —
    it cannot be silently dropped, so it is appended to `missing` as
    (dotted name, line) for the caller to flag."""
    s = const_str(node)
    if s is not None:
        return s, True
    if isinstance(node, ast.Attribute):
        dn = dotted_name(node)
        if dn is not None:
            head, _, attr = dn.rpartition(".")
            if head.split(".")[-1] == registry_class:
                if registry and attr not in registry \
                        and missing is not None:
                    missing.append((dn, node.lineno))
                return registry.get(attr), False
    return None, False


def _is_op_shaped(node: ast.AST) -> bool:
    """Does this expression look like it carries an op name at runtime?
    A bare name/attribute called `op`/`opname`, a `.get("op")` call, or
    a `msg["op"]` subscript."""
    dn = dotted_name(node)
    if dn is not None and dn.split(".")[-1] in ("op", "opname"):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and node.args
            and const_str(node.args[0]) == "op"):
        return True
    if isinstance(node, ast.Subscript) and const_str(node.slice) == "op":
        return True
    return False


def _collect_ops(sf: SourceFile, registry: dict[str, str],
                 missing: list, registry_class: str = _OP_REGISTRY_CLASS
                 ) -> tuple[list[_OpUse], list[_OpUse]]:
    """(produced, consumed) op uses in one file, resolved against ONE
    registry class (HostOp or LinkOp — references to the other class
    are invisible here and scanned by their own group); nonexistent
    registry attributes land in `missing` as (file, dotted, line)."""
    produced: list[_OpUse] = []
    consumed: list[_OpUse] = []
    miss: list = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if const_str(k) == "op":
                    val, raw = _op_value(v, registry,
                                         registry_class, miss)
                    if val is not None:
                        produced.append(_OpUse(val, v.lineno, raw, sf))
        elif isinstance(node, ast.Assign):
            # m["op"] = HostOp.STATS — the reply-in-place producer shape
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and const_str(t.slice) == "op"):
                    val, raw = _op_value(node.value, registry,
                                         registry_class, miss)
                    if val is not None:
                        produced.append(
                            _OpUse(val, node.value.lineno, raw, sf))
        elif isinstance(node, ast.Compare):
            # op == "submit" | "submit" == op | msg.get("op") == HostOp.X
            # | op in ("event", "events")
            sides = [node.left] + list(node.comparators)
            if not any(_is_op_shaped(s) for s in sides):
                continue
            for side in sides:
                if _is_op_shaped(side):
                    continue
                val, raw = _op_value(side, registry,
                                     registry_class, miss)
                if val is not None:
                    consumed.append(_OpUse(val, side.lineno, raw, sf))
                elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                    for elt in side.elts:
                        val, raw = _op_value(elt, registry,
                                             registry_class, miss)
                        if val is not None:
                            consumed.append(
                                _OpUse(val, elt.lineno, raw, sf))
    missing.extend((sf, dn, ln) for dn, ln in miss)
    return produced, consumed


def _collect_keys(sf: SourceFile, registry: dict[str, str],
                  missing: list) -> tuple[list[_OpUse], list[_OpUse]]:
    """(sent, handled) MessageKey uses in one file; nonexistent
    registry attributes land in `missing`."""
    values = set(registry.values())
    miss: list = []
    sent: list[_OpUse] = []
    handled: list[_OpUse] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SEND_METHODS and node.args):
                val, raw = _op_value(node.args[0], registry,
                                     _KEY_REGISTRY_CLASS, miss)
                if val is not None and (not raw or val in values):
                    sent.append(_OpUse(val, node.args[0].lineno, raw, sf))
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if not any(dotted_name(s) is not None
                       and dotted_name(s).split(".")[-1] == "key"
                       for s in sides):
                continue
            for side in sides:
                dn = dotted_name(side)
                if dn is not None and dn.split(".")[-1] == "key":
                    continue
                elts = (side.elts
                        if isinstance(side, (ast.Tuple, ast.List, ast.Set))
                        else [side])
                for elt in elts:
                    val, raw = _op_value(elt, registry,
                                         _KEY_REGISTRY_CLASS, miss)
                    if val is not None and (not raw or val in values):
                        handled.append(_OpUse(val, elt.lineno, raw, sf))
    missing.extend((sf, dn, ln) for dn, ln in miss)
    return sent, handled


def _missing_findings(missing: list) -> list[Finding]:
    """W104 findings for nonexistent registry attributes (HostOp.EVNT):
    an AttributeError waiting on a rarely-taken dispatch path."""
    return [Finding(
        checker=NAME, code="W104", path=sf.rel, line=ln, symbol=dn,
        message=(f'{dn} does not exist in the registry '
                 f'(symmetry_tpu/protocol/keys.py) — this is an '
                 f'AttributeError waiting on a rare dispatch path'))
        for sf, dn, ln in missing]


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    missing: list = []

    def _finding(code: str, use: _OpUse, msg: str) -> Finding:
        return Finding(checker=NAME, code=code, path=use.file.rel,
                       line=use.line, message=msg, symbol=use.value)

    def _scan_op_group(registry_class: str, group: tuple[str, ...],
                       label: str) -> None:
        """One producer/consumer agreement pass: W101–W104 for one op
        registry over its file group."""
        registry = project.class_constants(registry_class)
        values = set(registry.values())
        produced: list[_OpUse] = []
        consumed: list[_OpUse] = []
        miss: list = []
        for sf in project.select(group):
            p, c = _collect_ops(sf, registry, miss, registry_class)
            produced.extend(p)
            consumed.extend(c)
        findings.extend(_missing_findings(miss))
        for use in produced + consumed:
            if registry and use.raw and use.value in values:
                findings.append(_finding(
                    "W101", use,
                    f'raw op literal "{use.value}" — use '
                    f'{registry_class}.'
                    f'{next(k for k, v in registry.items() if v == use.value)}'
                    f' from symmetry_tpu/protocol/keys.py'))
            if registry and use.value not in values:
                findings.append(_finding(
                    "W104", use,
                    f'op "{use.value}" is not registered in '
                    f'{registry_class} (symmetry_tpu/protocol/keys.py) '
                    f'— unknown wire op'))
        produced_vals = {u.value for u in produced}
        consumed_vals = {u.value for u in consumed}
        for use in produced:
            if use.value not in consumed_vals:
                findings.append(_finding(
                    "W102", use,
                    f'op "{use.value}" is produced here but no consumer '
                    f'in the {label} group dispatches on it — the frame '
                    f'would be silently dropped'))
        for use in consumed:
            if use.value not in produced_vals:
                findings.append(_finding(
                    "W103", use,
                    f'op "{use.value}" is dispatched on here but '
                    f'nothing in the {label} group produces it — dead '
                    f'consumer or renamed producer'))

    # ---- host-pipe ops + handoff-link ops -----------------------------
    _scan_op_group(_OP_REGISTRY_CLASS, OP_GROUP, "host-pipe")
    _scan_op_group(_LINK_REGISTRY_CLASS, LINK_GROUP, "handoff-link")

    # ---- MessageKey tier ---------------------------------------------
    key_registry = project.class_constants(_KEY_REGISTRY_CLASS)
    if key_registry:
        key_values = set(key_registry.values())
        sent: list[_OpUse] = []
        handled: list[_OpUse] = []
        for sf in project.select(KEY_GROUP):
            s, h = _collect_keys(sf, key_registry, missing)
            sent.extend(s)
            handled.extend(h)
        findings.extend(_missing_findings(missing))
        for use in sent + handled:
            if use.raw and use.value in key_values:
                findings.append(_finding(
                    "W105", use,
                    f'raw message-key literal "{use.value}" — use '
                    f'MessageKey.'
                    f'{next(k for k, v in key_registry.items() if v == use.value)}'))
        sent_vals = {u.value for u in sent}
        handled_vals = {u.value for u in handled}
        for use in sent:
            if use.value not in handled_vals:
                findings.append(_finding(
                    "W106", use,
                    f'message key "{use.value}" is sent here but no '
                    f'peer-tier handler compares against it'))
        for use in handled:
            if use.value not in sent_vals:
                findings.append(_finding(
                    "W107", use,
                    f'message key "{use.value}" is handled here but '
                    f'nothing in the tier ever sends it'))
    return findings


SPEC = CheckerSpec(
    name=NAME,
    doc="host-pipe op / MessageKey producer-consumer agreement",
    run=check,
    codes=("W101", "W102", "W103", "W104", "W105", "W106", "W107"),
)
