"""Fleet telemetry: the always-on, label-aware metrics registry.

Everything this framework could report before PR 10 was a point-in-time
`stats()` snapshot pulled over the host pipe or the peer wire — no
standard scrape surface, no time series, no SLO evaluation. This module
is the missing layer:

  - `MetricsRegistry` (`METRICS`, process-global): Counter / Gauge /
    Histogram families with fixed label names, every mutation and every
    read under ONE lock so a snapshot is always consistent (the same
    contract Histogram.to_dict in utils/trace.py earned the hard way).
    Near-zero cost when disabled: one attribute load and a branch per
    call — asserted by a CI overhead guard, same discipline as the
    fault injector's no-op contract.
  - `MetricName`: the metric-name registry, protocol/keys.py-style. One
    place on purpose: the symlint metric-name checker (M101–M103,
    analysis/metric_names.py) fails CI on names emitted but not
    registered here, or registered but never emitted — a typo'd metric
    is a silently-empty dashboard panel, not an error.
  - Prometheus text exposition: `render_prometheus` merges one-or-many
    snapshots (provider process + engine host(s), each with extra
    labels like `tier="prefill"`) into the standard text format, and
    `MetricsServer` serves it on `metrics.port` with nothing but
    stdlib `http.server`. `parse_prometheus_text` is the inverse, for
    `tools/symtop.py` and the CI smoke.
  - `SloMonitor`: multiwindow burn-rate evaluation over the request
    stream (SRE-workbook shape: a breach requires BOTH the fast and
    the slow window to burn the error budget faster than the
    threshold, so a single slow request can't page and a sustained
    regression can't hide). Breaches are rate-limited, exported as
    registry metrics, and the caller (provider/provider.py) wires them
    to the flight recorder + a structured log event — SLO breach is a
    first-class, test-triggerable signal.

Histograms keep a bounded ring of recent (t, value) samples beside the
cumulative buckets — the time series a live `symtop` view or a windowed
percentile wants, at fixed memory.
"""

from __future__ import annotations

import bisect
import http.server
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable


class MetricName:
    """The metric-name registry. Every name the codebase emits lives
    here (and only names the codebase emits — the symlint metric-name
    checker enforces both directions). Prometheus conventions: `_total`
    for counters, `_seconds` for latency histograms, base units."""

    # --- provider tier (provider/provider.py, one per provider process)
    PROVIDER_REQUESTS = "sym_provider_requests_total"
    PROVIDER_TOKENS_OUT = "sym_provider_tokens_out_total"
    PROVIDER_ERRORS = "sym_provider_errors_total"
    PROVIDER_SHEDS = "sym_provider_sheds_total"              # {reason}
    PROVIDER_IN_FLIGHT = "sym_provider_in_flight"
    PROVIDER_PENDING_FIRST_TOKEN = "sym_provider_pending_first_token"
    PROVIDER_CONNECTIONS = "sym_provider_connections"
    PROVIDER_UPTIME = "sym_provider_uptime_seconds"
    PROVIDER_TTFT = "sym_provider_ttft_seconds"
    PROVIDER_E2E = "sym_provider_e2e_seconds"
    PROVIDER_INTER_CHUNK = "sym_provider_inter_chunk_seconds"
    PROVIDER_BACKEND_RESTARTS = "sym_provider_backend_restarts_total"
    PROVIDER_FLIGHT_DUMPS = "sym_provider_flight_dumps_total"  # {reason}

    # --- SLO monitor (this module; wired by the provider)
    SLO_BURN_RATE = "sym_slo_burn_rate"                      # {slo,window}
    SLO_BREACHES = "sym_slo_breaches_total"                  # {slo}

    # --- stream resumption (provider relay + scheduler admission): the
    #     crash-surviving generation path. `resumed_tokens` = tokens a
    #     resume skipped regenerating (the saved work); `wasted_tokens` =
    #     overlap tokens the relay's offset dedup dropped (work the
    #     engine redid that the client already had); `resume_ttft` =
    #     interruption → first CONTINUATION token, the recovery-latency
    #     headline of the kill-under-load round.
    PROVIDER_RESUMES = "sym_resume_requests_total"
    RESUME_WASTED_TOKENS = "sym_resume_wasted_tokens_total"
    RESUME_TTFT = "sym_resume_ttft_seconds"
    SCHED_RESUMES = "sym_resume_admissions_total"
    SCHED_RESUMED_TOKENS = "sym_resume_resumed_tokens_total"
    SCHED_RESUME_REUSED = "sym_resume_reused_tokens_total"

    # --- relay / per-stage TTFT (provider/backends/tpu_native.py)
    TTFT_STAGE = "sym_ttft_stage_seconds"                    # {stage}
    RELAY_HOST_FRAMES = "sym_relay_host_frames_total"
    RELAY_HOST_EVENTS = "sym_relay_host_events_total"

    # --- scheduler (engine/scheduler.py; host process in process mode,
    #     tier-labeled through the HostOp.METRICS probe)
    SCHED_REQUESTS = "sym_sched_requests_total"
    SCHED_TOKENS = "sym_sched_tokens_total"
    SCHED_QUEUE_DEPTH = "sym_sched_queue_depth"
    SCHED_OCCUPANCY = "sym_sched_occupancy"
    SCHED_EVICTIONS = "sym_sched_evictions_total"
    SCHED_DEADLINE_SHEDS = "sym_sched_deadline_sheds_total"
    SCHED_HANDOFFS = "sym_sched_handoffs_total"
    SCHED_DISPATCH = "sym_sched_dispatch_seconds"            # {kind}
    SCHED_TTFT = "sym_sched_ttft_seconds"
    # Overlapped-pipeline split (tpu.pipeline_depth): wall the dispatch
    # thread spends per non-idle loop iteration vs wall the emit worker
    # spends delivering the offloaded per-block work, plus the live
    # in-flight block count between iterations. dispatch_thread -> ~the
    # bare dispatch cost is the CPU-verifiable proxy for
    # sym_dispatch_gap_share -> ~0 on the chip.
    SCHED_DISPATCH_THREAD = "sym_sched_dispatch_thread_s"
    SCHED_OFFLOADED = "sym_sched_offloaded_s"
    SCHED_PIPELINE_DEPTH = "sym_sched_pipeline_depth"

    # --- symprof device-time attribution (utils/devprof.py; lives in
    #     the host process beside the engine, tier-labeled through the
    #     HostOp.METRICS probe). Device durations come from sampling
    #     completion probes (`tpu.profile_sample`); the dispatch gap is
    #     host idle between a probed device completion and the next
    #     dispatch — the steady-wire suspect, measured on-device.
    DEVICE_DISPATCH = "sym_device_dispatch_seconds"          # {kind}
    DEVICE_PROBES = "sym_device_probes_total"                # {kind}
    DISPATCH_GAP = "sym_dispatch_gap_seconds"
    DISPATCH_GAP_SHARE = "sym_dispatch_gap_share"
    # On-demand jax.profiler captures (provider wire op / SIGUSR1 / SLO
    # burn hook → HostOp.PROFILE), booked by the provider per trigger.
    PROFILE_CAPTURES = "sym_profile_captures_total"          # {reason}

    # --- radix prefix cache (engine/prefix_cache.py; lives in the host
    #     process, tier-labeled through the HostOp.METRICS probe)
    PREFIX_BLOCKS_IN_USE = "sym_prefix_blocks_in_use"
    PREFIX_BLOCKS_EVICTED = "sym_prefix_blocks_evicted_total"
    PREFIX_HIT_DEPTH = "sym_prefix_radix_hit_depth_blocks"

    # --- fused-dequant degrade ledger (engine/engine.py): one count per
    #     int8 weight leaf that stays on the XLA mixed dot instead of
    #     the packed W8A16 kernel at load, labeled with the degrade
    #     reason (untileable | shard_indivisible | shard_untileable |
    #     expert_stack | stage_axis). Booked so a mesh build that quietly
    #     lost its fused leaves shows up in symtop, never as a silent
    #     bandwidth regression.
    QMM_FALLBACK = "sym_qmm_fallback_total"                  # {reason}

    # --- engine host pipe (engine/host.py)
    HOST_PIPE_WRITES = "sym_host_pipe_writes_total"
    HOST_PIPE_BYTES = "sym_host_pipe_bytes_total"
    HOST_PIPE_EVENTS = "sym_host_pipe_events_total"
    HOST_HANDOFF_FRAMES = "sym_host_handoff_frames_total"
    HOST_HANDOFF_BYTES = "sym_host_handoff_bytes_total"
    HOST_HANDOFF_SERIALIZE = "sym_host_handoff_serialize_seconds"
    HOST_ADOPT_FRAMES = "sym_host_adopt_frames_total"        # {outcome}
    HOST_ADOPT_DESERIALIZE = "sym_host_adopt_deserialize_seconds"

    # --- disagg broker, provider process (engine/disagg/broker.py)
    HANDOFF_FRAMES = "sym_handoff_frames_total"
    HANDOFF_BYTES = "sym_handoff_bytes_total"
    HANDOFF_PENDING = "sym_handoff_pending"
    HANDOFF_WIRE = "sym_handoff_wire_seconds"
    HANDOFF_PREFILL_TIER = "sym_handoff_prefill_tier_seconds"

    # --- handoff link (engine/disagg/net.py; decode side + inline node)
    LINK_CONNECTS = "sym_link_connects_total"
    LINK_DROPS = "sym_link_drops_total"
    LINK_CONNECTED = "sym_link_connected"
    LINK_WIRE_FRAMES = "sym_link_wire_frames_total"
    LINK_WIRE_BYTES = "sym_link_wire_bytes_total"
    LINK_RETRIES = "sym_link_retries_total"
    LINK_CREDIT_STALLS = "sym_link_credit_stalls_total"
    LINK_PARTIAL_DISCARDS = "sym_link_partial_discards_total"

    # --- elastic disagg pool (engine/disagg/pool.py, provider process)
    POOL_MEMBERS = "sym_pool_members"                        # {tier}
    POOL_HEALTHY = "sym_pool_healthy"                        # {tier}
    POOL_MEMBER_STATE = "sym_pool_member_state"              # {tier,node}
    POOL_PLACEMENTS = "sym_pool_placements_total"            # {tier,node}
    POOL_REPLACEMENTS = "sym_pool_replacements_total"
    POOL_DRAINS = "sym_pool_drains_total"
    # Cache-aware placement (gossiped radix summaries as the signal):
    # predicted hit depth actually banked per placement, placements
    # split by whether affinity changed the answer, and the age of each
    # member's last gossiped summary (the staleness-decay input).
    POOL_PREDICTED_HIT = "sym_pool_predicted_hit_blocks"     # {tier,node}
    POOL_AFFINITY_PLACEMENTS = (
        "sym_pool_affinity_placements_total")                # {outcome}
    POOL_GOSSIP_AGE = "sym_pool_gossip_age_seconds"          # {tier,node}

    # --- SLO-goodput autoscaler (engine/disagg/autoscale.py, provider
    #     process). Decisions count only real topology changes —
    #     hold/dwell/cooldown ticks stay out of the counter so
    #     decisions/min in symtop means "the shape moved". Target vs
    #     live membership is the convergence view; chip-seconds is
    #     Σ member-alive time (the goodput denominator, gauge because
    #     it is recomputed from the router's ledger each tick).
    AUTOSCALE_DECISIONS = "sym_autoscale_decisions_total"    # {action,tier}
    AUTOSCALE_TARGET = "sym_autoscale_target_members"        # {tier}
    AUTOSCALE_CHIP_SECONDS = "sym_autoscale_chip_seconds"
    AUTOSCALE_GOODPUT = "sym_autoscale_goodput_tokens_per_chip_s"
    # Pre-ledger continuity series: the raw cumulative token count the
    # goodput numerator used before symledger wired SLO attainment in
    # (PR 20) — dashboards comparing old and new goodput read both.
    AUTOSCALE_TOKENS_RAW = "sym_autoscale_tokens_raw"

    # --- symledger per-request cost attribution (engine/ledger.py →
    #     provider/provider.py, tpu.ledger). device_seconds is a
    #     histogram per phase (prefill/chunk/decode/verify/adopt);
    #     wasted_seconds counts device time spent on output nobody
    #     consumed, per reason (spec_rejected/resume_discarded/
    #     deadline_shed/killed_prefill/cancelled); goodput is the
    #     windowed SLO objective — SLO-attaining tokens over attributed
    #     device seconds (DistServe's goodput, per request).
    REQUEST_DEVICE_SECONDS = "sym_request_device_seconds"    # {phase}
    REQUEST_WASTED_SECONDS = "sym_request_wasted_seconds"    # {reason}
    GOODPUT_TOKENS_PER_DEVICE_S = "sym_goodput_tokens_per_device_second"

    # --- server registry (server/registry.py)
    SERVER_PROVIDERS_ONLINE = "sym_server_providers_online"
    SERVER_PROVIDER_QUEUED = "sym_server_provider_queued"    # {provider,model}


METRIC_NAMES = frozenset(
    v for k, v in vars(MetricName).items()
    if not k.startswith("_") and isinstance(v, str)
)

# Default latency buckets: log-ish spacing 1 ms .. 60 s — every latency
# this framework measures, 17 buckets (+Inf implied). Fixed tuple so two
# processes' histograms always merge bucket-for-bucket.
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 30.0, 45.0, 60.0)

# Recent-sample ring per histogram series: the bounded time series a
# live view reads (fixed memory; ~16 B/sample).
RING_CAPACITY = 512


class _Family:
    __slots__ = ("name", "kind", "help", "label_names", "series",
                 "buckets")

    def __init__(self, name: str, kind: str, help_: str,
                 label_names: tuple[str, ...],
                 buckets: tuple[float, ...] | None = None) -> None:
        self.name = name
        self.kind = kind                      # counter | gauge | histogram
        self.help = help_
        self.label_names = label_names
        self.buckets = buckets
        # label-values tuple -> float (counter/gauge) or
        # [count, sum, min, max, bucket_counts list, ring deque]
        self.series: dict[tuple[str, ...], Any] = {}


class _Handle:
    """One family's mutation handle. Label values ride as kwargs and
    must name the family's declared label set (missing labels become
    ""); the branch on `enabled` is the whole disabled-mode cost."""

    __slots__ = ("_reg", "_fam")

    def __init__(self, reg: "MetricsRegistry", fam: _Family) -> None:
        self._reg = reg
        self._fam = fam

    def _key(self, labels: dict[str, Any]) -> tuple[str, ...]:
        return tuple(str(labels.get(n, "")) for n in self._fam.label_names)

    def remove(self, **labels: Any) -> None:
        """Drop one labeled series (e.g. a provider that left the
        fleet) — labeled series otherwise live forever, and a gauge for
        a dead label set keeps exporting its last value."""
        with self._reg._lock:
            self._fam.series.pop(self._key(labels), None)


class Counter(_Handle):
    def inc(self, n: float = 1.0, **labels: Any) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        key = self._key(labels)
        with reg._lock:
            self._fam.series[key] = self._fam.series.get(key, 0.0) + n

    def value(self, **labels: Any) -> float:
        with self._reg._lock:
            return float(self._fam.series.get(self._key(labels), 0.0))


class Gauge(_Handle):
    def set(self, value: float, **labels: Any) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self._fam.series[self._key(labels)] = float(value)

    def add(self, n: float = 1.0, **labels: Any) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        key = self._key(labels)
        with reg._lock:
            self._fam.series[key] = self._fam.series.get(key, 0.0) + n

    def value(self, **labels: Any) -> float:
        with self._reg._lock:
            return float(self._fam.series.get(self._key(labels), 0.0))


class HistogramMetric(_Handle):
    def observe(self, value: float, **labels: Any) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        value = float(value)
        key = self._key(labels)
        fam = self._fam
        with reg._lock:
            s = fam.series.get(key)
            if s is None:
                s = [0, 0.0, value, value,
                     [0] * (len(fam.buckets) + 1),
                     deque(maxlen=RING_CAPACITY)]
                fam.series[key] = s
            s[0] += 1
            s[1] += value
            s[2] = min(s[2], value)
            s[3] = max(s[3], value)
            s[4][bisect.bisect_left(fam.buckets, value)] += 1
            s[5].append((time.monotonic(), value))


class MetricsRegistry:
    """Process-global metric families behind one lock.

    One lock on purpose: every snapshot is then a consistent cut of
    every family at once (a fleet view comparing `requests_total`
    against `tokens_out_total` must never see one family mid-update),
    and multi-thread increments are exact by construction — the
    concurrency regression test pins this. The per-operation cost is a
    short critical section at block/dispatch granularity, never per
    token."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # ------------------------------------------------------- registration

    def _family(self, name: str, kind: str, help_: str,
                labels: Iterable[str],
                buckets: tuple[float, ...] | None = None) -> _Family:
        label_names = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_, label_names, buckets)
                if kind in ("counter", "gauge") and not label_names:
                    # Materialize the unlabeled series at registration:
                    # a scrape then shows the family at 0 from the first
                    # request on — an empty counter is a statement, a
                    # missing one is a question. (Labeled families and
                    # histograms appear on first emission, the standard
                    # Prometheus-client behavior.)
                    fam.series[()] = 0.0
                self._families[name] = fam
            elif (fam.kind != kind or fam.label_names != label_names
                  or (buckets is not None and fam.buckets != buckets)):
                raise ValueError(
                    f"metric {name!r} re-registered as {kind}"
                    f"{label_names} buckets={buckets} (was {fam.kind}"
                    f"{fam.label_names} buckets={fam.buckets})")
            return fam

    def counter(self, name: str, help_: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return Counter(self, self._family(name, "counter", help_, labels))

    def gauge(self, name: str, help_: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return Gauge(self, self._family(name, "gauge", help_, labels))

    def histogram(self, name: str, help_: str = "",
                  labels: Iterable[str] = (),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS
                  ) -> HistogramMetric:
        return HistogramMetric(
            self, self._family(name, "histogram", help_, labels, buckets))

    # ---------------------------------------------------------- snapshot

    def snapshot(self, compact: bool = False) -> dict[str, Any]:
        """Every family and series as one consistent JSON-able cut.
        `compact` drops the recent-sample rings (the wire/bench shape —
        rings are for the process-local live view)."""
        with self._lock:
            families: dict[str, Any] = {}
            for name, fam in self._families.items():
                series = []
                for key, s in fam.series.items():
                    labels = dict(zip(fam.label_names, key))
                    if fam.kind == "histogram":
                        entry: dict[str, Any] = {
                            "labels": labels, "count": s[0],
                            "sum": round(s[1], 6),
                            "min": s[2], "max": s[3],
                            "buckets": [
                                [le, c] for le, c in
                                zip(list(fam.buckets) + ["+Inf"],
                                    _cumulative(s[4]))],
                        }
                        if not compact:
                            entry["recent"] = [[round(t, 4), v]
                                               for t, v in s[5]]
                    else:
                        entry = {"labels": labels, "value": s}
                    series.append(entry)
                families[name] = {"kind": fam.kind, "help": fam.help,
                                  "labels": list(fam.label_names),
                                  "series": series}
            return {"t_mono": time.monotonic(), "enabled": self.enabled,
                    "families": families}

    def reset(self) -> None:
        """Drop every family (tests; a prod process never resets)."""
        with self._lock:
            self._families.clear()


def _cumulative(counts: list[int]) -> list[int]:
    out, acc = [], 0
    for c in counts:
        acc += c
        out.append(acc)
    return out


# The process-global registry: one per OS process (provider, engine
# host, prefill node each own theirs), merged at exposition time with
# per-process extra labels (tier=...).
METRICS = MetricsRegistry()


# ----------------------------------------------------------- exposition


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(labels.items()) if v != "")
    return "{" + inner + "}" if inner else ""


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(snapshots: list[dict[str, Any]]) -> str:
    """Merge snapshots into Prometheus text exposition format.

    Each entry is `{"snapshot": <MetricsRegistry.snapshot()>,
    "labels": {...}}` — the extra labels (e.g. `tier="prefill"`) stamp
    every series of that snapshot, which is how one provider's endpoint
    exposes its own process plus its engine host(s) as one scrape."""
    # family name -> (kind, help, [(labels, entry)...])
    merged: dict[str, tuple[str, str, list]] = {}
    order: list[str] = []
    for item in snapshots:
        snap = item.get("snapshot") or {}
        extra = dict(item.get("labels") or {})
        for name, fam in (snap.get("families") or {}).items():
            if name not in merged:
                merged[name] = (fam.get("kind", "gauge"),
                                fam.get("help", ""), [])
                order.append(name)
            for s in fam.get("series") or []:
                labels = {**(s.get("labels") or {}), **extra}
                merged[name][2].append((labels, s))
    lines: list[str] = []
    for name in order:
        kind, help_, series = merged[name]
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        for labels, s in series:
            if kind == "histogram":
                for le, c in s.get("buckets") or []:
                    lines.append(
                        f"{name}_bucket"
                        f"{_label_str({**labels, 'le': str(le)})} {c}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_fmt(s.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{s.get('count', 0)}")
            else:
                lines.append(
                    f"{name}{_label_str(labels)} "
                    f"{_fmt(s.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict[str, dict[str, Any]]:
    """The inverse of render_prometheus, enough for symtop and the CI
    smoke: `{family: {"kind", "series": [{"labels", "value"}]}}`.
    Histogram `_bucket`/`_sum`/`_count` sample lines fold back under
    their family name with the suffix recorded per sample."""
    fams: dict[str, dict[str, Any]] = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        # name{labels} value   |   name value
        brace = line.find("{")
        labels: dict[str, str] = {}
        if brace >= 0:
            name = line[:brace]
            end = line.rfind("}")
            body, rest = line[brace + 1:end], line[end + 1:]
            for part in _split_labels(body):
                if "=" in part:
                    k, v = part.split("=", 1)
                    labels[k.strip()] = v.strip().strip('"')
        else:
            name, _, rest = line.partition(" ")
        try:
            value = float(rest.strip())
        except ValueError:
            continue
        base, suffix = name, ""
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[:-len(suf)] in types:
                base, suffix = name[:-len(suf)], suf
                break
        fam = fams.setdefault(base, {"kind": types.get(base, "untyped"),
                                     "series": []})
        fam["series"].append({"labels": labels, "value": value,
                              "suffix": suffix})
    return fams


def _split_labels(body: str) -> list[str]:
    """Split a label body on commas outside quotes."""
    out, cur, quoted = [], [], False
    for ch in body:
        if ch == '"':
            quoted = not quoted
        if ch == "," and not quoted:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def histogram_quantile(buckets: list[tuple[float, float]],
                       q: float) -> float | None:
    """Prometheus-style quantile estimate from cumulative (le, count)
    buckets (le may be the string "+Inf"). Linear interpolation within
    the winning bucket; None when empty."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, c in buckets:
        bound = float("inf") if le in ("+Inf", float("inf")) else float(le)
        if c >= rank:
            if bound == float("inf"):
                return prev_le or None
            if c == prev_c:
                return bound
            return prev_le + (bound - prev_le) * (rank - prev_c) / (c - prev_c)
        prev_le, prev_c = (0.0 if bound == float("inf") else bound), c
    return prev_le or None


class MetricsServer:
    """Prometheus exposition endpoint on stdlib http.server.

    One daemon thread, GET /metrics → `render()` (a callable returning
    the exposition text — the provider's bridges into its event loop).
    Port 0 binds ephemeral; `.port` is the bound port either way."""

    def __init__(self, render: Callable[[], str],
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self._render = render
        self._host = host
        self._want_port = port
        self._httpd: http.server.ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        assert self._httpd is not None, "metrics server not started"
        return self._httpd.server_address[1]

    def start(self) -> None:
        render = self._render

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] != "/metrics":
                    self.send_error(404)
                    return
                try:
                    body = render().encode("utf-8")
                except Exception as exc:  # noqa: BLE001 — scrape must not die
                    self.send_error(500, str(exc)[:80])
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass  # scrapes must not spam stderr

        self._httpd = http.server.ThreadingHTTPServer(
            (self._host, self._want_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------- SLO monitor


class _BurnWindow:
    """One sliding window's good/bad tallies, O(1) amortized per event:
    counts move incrementally on append/evict instead of rescanning the
    deque — observe() sits on the per-chunk streaming hot path, and a
    full-window scan there would inflate the very inter-chunk gaps it
    measures."""

    __slots__ = ("window_s", "events", "good", "bad")

    MAX_EVENTS = 65536  # absolute cap (fixed memory)

    def __init__(self, window_s: float) -> None:
        self.window_s = window_s
        self.events: deque = deque()
        self.good = 0
        self.bad = 0

    def _evict_one(self) -> None:
        _, was_ok = self.events.popleft()
        if was_ok:
            self.good -= 1
        else:
            self.bad -= 1

    def add(self, t: float, ok: bool) -> None:
        if len(self.events) >= self.MAX_EVENTS:
            self._evict_one()
        self.events.append((t, ok))
        if ok:
            self.good += 1
        else:
            self.bad += 1

    def prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self.events and self.events[0][0] < horizon:
            self._evict_one()

    def burn(self, budget: float) -> tuple[float, int]:
        total = self.good + self.bad
        if total == 0:
            return 0.0, 0
        return (self.bad / total) / budget, total


class SloMonitor:
    """Multiwindow burn-rate evaluation over good/bad request events.

    Config (the provider's `slo:` block; every key optional except at
    least one target):

        slo:
          ttft_s: 2.0            # TTFT target — over it, the event is bad
          inter_chunk_s: 1.0     # inter-chunk gap target
          objective: 0.99        # fraction of events that must be good
          fast_window_s: 300.0   # fast burn window
          slow_window_s: 3600.0  # slow burn window
          burn_threshold: 10.0   # breach when BOTH windows burn >= this
          min_samples: 12        # slow window needs this many events
          min_interval_s: 300.0  # rate limit between breach events

    Burn rate = (bad fraction in window) / (1 - objective): 1.0 means
    the error budget is being spent exactly at the sustainable rate,
    `burn_threshold` means that many times faster. Requiring both
    windows is the standard multiwindow guard: the fast window makes
    the signal responsive, the slow window keeps one bad burst from
    paging — and `min_samples` keeps the slow window honest while it is
    still cold (right after startup both windows hold the SAME few
    events, so without a floor one slow cold-start request would page a
    healthy fleet). `clock` is injectable so tests drive the windows
    deterministically."""

    def __init__(self, config: dict[str, Any] | None, *,
                 clock: Callable[[], float] = time.monotonic,
                 on_breach: Callable[[dict[str, Any]], None] | None = None
                 ) -> None:
        cfg = dict(config or {})
        self.objective = float(cfg.get("objective", 0.99))
        self.fast_window_s = float(cfg.get("fast_window_s", 300.0))
        self.slow_window_s = float(cfg.get("slow_window_s", 3600.0))
        self.burn_threshold = float(cfg.get("burn_threshold", 10.0))
        self.min_interval_s = float(cfg.get("min_interval_s", 300.0))
        self.min_samples = int(cfg.get("min_samples", 12))
        self.targets: dict[str, float] = {}
        for key, name in (("ttft_s", "ttft"),
                          ("inter_chunk_s", "inter_chunk"),
                          ("e2e_s", "e2e")):
            if cfg.get(key) is not None:
                self.targets[name] = float(cfg[key])
        self._clock = clock
        self._on_breach = on_breach
        self._lock = threading.Lock()
        self._windows: dict[str, tuple[_BurnWindow, _BurnWindow]] = {
            name: (_BurnWindow(self.fast_window_s),
                   _BurnWindow(self.slow_window_s))
            for name in self.targets}
        self._last_breach: dict[str, float] = {}
        self._burn_gauge = METRICS.gauge(
            MetricName.SLO_BURN_RATE,
            "error-budget burn rate per SLO and window",
            labels=("slo", "window"))
        self._breach_counter = METRICS.counter(
            MetricName.SLO_BREACHES,
            "SLO burn-rate breach events", labels=("slo",))

    @property
    def enabled(self) -> bool:
        return bool(self.targets)

    def observe(self, slo: str, value_s: float) -> dict[str, Any] | None:
        """Record one measurement and evaluate its rule. Returns the
        breach event when this observation tips (or keeps) both windows
        over the threshold and the rate limit allows one, else None."""
        target = self.targets.get(slo)
        if target is None:
            return None
        now = self._clock()
        ok = value_s <= target
        with self._lock:
            for w in self._windows[slo]:
                w.add(now, ok)
        return self._evaluate_one(slo, now)

    def _evaluate_one(self, slo: str, now: float) -> dict[str, Any] | None:
        budget = max(1.0 - self.objective, 1e-9)
        with self._lock:
            fast_w, slow_w = self._windows[slo]
            fast_w.prune(now)
            slow_w.prune(now)
            fast, n_fast = fast_w.burn(budget)
            slow, n_slow = slow_w.burn(budget)
        self._burn_gauge.set(round(fast, 3), slo=slo, window="fast")
        self._burn_gauge.set(round(slow, 3), slo=slo, window="slow")
        if (n_slow < self.min_samples
                or fast < self.burn_threshold
                or slow < self.burn_threshold):
            return None
        with self._lock:
            last = self._last_breach.get(slo, -1e18)
            if now - last < self.min_interval_s:
                return None
            self._last_breach[slo] = now
        self._breach_counter.inc(slo=slo)
        event = {"slo": slo, "target_s": self.targets[slo],
                 "objective": self.objective,
                 "burn_fast": round(fast, 3), "burn_slow": round(slow, 3),
                 "fast_window_s": self.fast_window_s,
                 "slow_window_s": self.slow_window_s,
                 "burn_threshold": self.burn_threshold,
                 "samples_fast": n_fast, "samples_slow": n_slow,
                 "t_mono": round(now, 4)}
        if self._on_breach is not None:
            self._on_breach(event)
        return event

    def burn_rate(self, now: float | None = None) -> float:
        """Current worst fast-window burn across every configured SLO,
        pruned live — the placement input the elastic disagg pool's
        router consumes (PoolRouter.update_gauges burn_rate): a tier
        that is burning error budget should stop winning placement
        ties. 0.0 when no SLO is configured or nothing has burned."""
        if not self.targets:
            return 0.0
        now = self._clock() if now is None else now
        budget = max(1.0 - self.objective, 1e-9)
        worst = 0.0
        with self._lock:
            for fast_w, _slow_w in self._windows.values():
                fast_w.prune(now)
                burn, _n = fast_w.burn(budget)
                worst = max(worst, burn)
        return worst

    def burn_rates(self, now: float | None = None) -> dict[str, float]:
        """Per-SLO fast-window burns, pruned live — the autoscaler's
        tier-pressure input: `ttft` burn implicates the prefill tier,
        `inter_chunk` the decode tier (burn_rate() collapses both into
        one worst-case number, which can place but cannot steer).
        Empty dict when no SLO is configured."""
        if not self.targets:
            return {}
        now = self._clock() if now is None else now
        budget = max(1.0 - self.objective, 1e-9)
        out: dict[str, float] = {}
        with self._lock:
            for slo, (fast_w, _slow_w) in self._windows.items():
                fast_w.prune(now)
                burn, _n = fast_w.burn(budget)
                out[slo] = burn
        return out

    def evaluate(self, now: float | None = None) -> list[dict[str, Any]]:
        """Evaluate every rule (periodic path — observe() already
        evaluates inline); returns the breach events triggered."""
        now = self._clock() if now is None else now
        out = []
        for slo in self.targets:
            ev = self._evaluate_one(slo, now)
            if ev is not None:
                out.append(ev)
        return out
