"""Version-compat shims for the JAX surface this project rides.

One module, one job: paper over the API moves between the JAX versions
the toolchain images carry, so the rest of the codebase imports ONE
spelling and never version-sniffs inline.

`shard_map`: promoted from `jax.experimental.shard_map.shard_map` to
`jax.shard_map` in newer releases (and the experimental module is slated
for removal). Older trees (e.g. 0.4.x) only have the experimental
spelling; newer ones may only have the top-level one. Resolved ONCE at
import; call sites (`parallel/ring.py`, `parallel/ulysses.py`,
`parallel/pipeline.py`) take it from here.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-promotion JAX: the experimental module is the only home
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map"]
