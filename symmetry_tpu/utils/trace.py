"""Tracing and serving metrics (SURVEY §5.1).

The reference ships no tracing of its own — its dependency stack embeds
hypertrace hooks it never enables, and the only counters are wire-level
byte counts on the peer object. Here the equivalents are first-class:

  - Span/Tracer: per-request spans (receive → first-token → end) with a
    bounded in-memory ring, cheap enough to leave on. Each provider owns
    one Tracer instance (provider/provider.py) whose histograms back its
    stats() snapshot.
  - Histogram: log-bucketed latency/throughput distributions with
    percentile estimates — p50/p99 TTFT is the BASELINE.json north-star
    metric, so it must be computable from a running provider, not from
    offline logs.
  - device_trace: on-demand jax.profiler capture for the TPU engine (the
    "trace capture endpoint" of SURVEY §5.1); writes a TensorBoard-loadable
    trace directory.

Request-scoped distributed tracing (PR 5) builds on the same rings:

  - Every span may carry a `trace_id` minted at the client (new_trace_id)
    and propagated client → provider → host → scheduler, so one request's
    spans correlate across four processes.
  - clock_handshake_offset reconciles the processes onto ONE clock: an
    NTP-style midpoint estimate from round-trip samples (min-RTT sample
    wins), replacing the old assume-zero-offset + clamp-negative-spans
    policy in the per-stage TTFT attribution.
  - Tracer.counter records bounded gauge tracks (queue depth, slot
    occupancy) beside the span ring.
  - export_perfetto merges many components' span/counter rings into one
    Chrome-trace-event JSON (one "process" row per component, one thread
    row per request) loadable in Perfetto / chrome://tracing.
  - FlightRecorder: always-on last-N-seconds dump — the rings are already
    bounded and always recording; a trigger (latency SLO breach, engine
    error, SIGUSR2) snapshots the merged recent timeline plus a stats()
    snapshot to a JSON file, so the LAST bad request is debuggable after
    the fact, not just the next one.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import math
import os
import random
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator


def _log_buckets(lo: float, hi: float, per_decade: int = 5) -> list[float]:
    n = max(1, int(math.ceil(math.log10(hi / lo) * per_decade)))
    ratio = (hi / lo) ** (1.0 / n)
    return [lo * ratio**i for i in range(n + 1)]


class Histogram:
    """Log-bucketed histogram + bounded raw-sample reservoir.

    Fixed memory, O(log buckets) observe, thread-safe. Default span covers
    0.1 ms .. 100 s — every latency this framework measures.

    Percentiles come from the RESERVOIR, not the buckets: round 4 shipped
    bench captures where provider TTFT p50 == p99 because 5-buckets-per-
    decade (1.58x per bucket) collapsed the whole distribution into one
    bucket — percentiles quoted to milliseconds carried ±26% bucket error.
    Up to `reservoir` observations the percentile is EXACT (every sample
    retained); beyond that, uniform reservoir sampling (Vitter's R) keeps
    an unbiased sample so the estimate degrades gracefully instead of
    quantizing. The buckets stay (20/decade now, ±5.9%) as the bounded
    all-time record behind mean/min/max and cross-checks.
    """

    RESERVOIR = 4096

    def __init__(self, lo: float = 1e-4, hi: float = 100.0,
                 per_decade: int = 20, reservoir: int | None = None) -> None:
        self._edges = _log_buckets(lo, hi, per_decade)
        self._counts = [0] * (len(self._edges) + 1)
        self._lock = threading.Lock()
        self._cap = reservoir if reservoir is not None else self.RESERVOIR
        self._samples: list[float] = []
        # Seeded per-instance PRNG: reservoir eviction must not perturb
        # (or be perturbed by) the global `random` stream, and seeding
        # keeps test runs reproducible.
        self._rng = random.Random(0x5EED)
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def observe(self, value: float) -> None:
        idx = bisect.bisect_right(self._edges, value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            if len(self._samples) < self._cap:
                self._samples.append(value)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._samples[j] = value

    @staticmethod
    def _rank(xs: list[float], p: float) -> float | None:
        if not xs:
            return None
        rank = min(len(xs) - 1, max(0, math.ceil(p / 100.0 * len(xs)) - 1))
        return xs[rank]

    def percentile(self, p: float) -> float | None:
        """p-th percentile (0-100); None when empty. Exact while the
        stream fits the reservoir, an unbiased estimate beyond."""
        with self._lock:
            xs = sorted(self._samples)
        return self._rank(xs, p)

    @property
    def mean(self) -> float | None:
        # count and total are read under the lock as ONE snapshot: a
        # concurrent observe() between the two reads would pair a new
        # total with a stale count (a mean no real prefix of the stream
        # ever had).
        with self._lock:
            return self.total / self.count if self.count else None

    def to_dict(self) -> dict[str, Any]:
        # One consistent snapshot under the lock: count/total/min/max and
        # the reservoir are mutated together by observe(), so reading
        # them piecemeal (the old property-per-field path) could return
        # e.g. count=N with the min of observation N+1.
        with self._lock:
            count, total = self.count, self.total
            mn, mx = self.min, self.max
            xs = sorted(self._samples)
        return {
            "count": count,
            "mean": total / count if count else None,
            "min": mn,
            "max": mx,
            "p50": self._rank(xs, 50),
            "p90": self._rank(xs, 90),
            "p99": self._rank(xs, 99),
        }


def new_trace_id() -> str:
    """Mint a request trace id (carried client → provider → host →
    scheduler so every component's spans correlate)."""
    return uuid.uuid4().hex[:16]


def clock_handshake_offset(
        samples: list[tuple[float, float, float]]) -> float:
    """Estimate a remote clock's offset from round-trip samples.

    Each sample is (t_send_local, t_remote, t_recv_local): the local
    stamps bracket the remote's clock read. The NTP midpoint estimate
    assumes the remote read happened halfway through the round trip, so
    its error is bounded by ±rtt/2 — the sample with the smallest RTT
    gives the tightest bound and wins.

    Returns `offset = remote_clock - local_clock`; map a remote stamp
    onto the local clock with `t_local = t_remote - offset`.
    """
    if not samples:
        return 0.0
    t0, tr, t1 = min(samples, key=lambda s: s[2] - s[0])
    return tr - (t0 + t1) / 2.0


@dataclass(slots=True)
class Span:
    """One completed timed section."""

    name: str
    start: float          # time.monotonic()
    duration_s: float
    request_id: str = ""
    trace_id: str = ""
    attrs: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "start": self.start,
                "duration_s": self.duration_s,
                "request_id": self.request_id,
                "trace_id": self.trace_id, **self.attrs}


class Tracer:
    """Bounded ring of completed spans + named histograms.

    Instantiate one per component that needs isolated metrics (the
    provider owns one); hot-path cost when disabled is a single attribute
    check.
    """

    def __init__(self, capacity: int = 4096) -> None:
        self.enabled = True
        self._spans: deque[Span] = deque(maxlen=capacity)
        # Gauge tracks (queue depth, slot occupancy): (t, name, value)
        # triples in one bounded ring — same always-on cost model as the
        # span ring, exported as Perfetto counter tracks.
        self._counters: deque[tuple[float, str, float]] = deque(
            maxlen=capacity)
        self._hists: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, request_id: str = "", trace_id: str = "",
             **attrs: Any) -> Iterator[dict[str, Any]]:
        """Times the enclosed block. Yields the attrs dict so the block can
        annotate the span (e.g. token counts) before it closes."""
        if not self.enabled:
            yield attrs
            return
        t0 = time.monotonic()
        try:
            yield attrs
        finally:
            self.record(name, t0, time.monotonic() - t0,
                        request_id=request_id, trace_id=trace_id, **attrs)

    def record(self, name: str, start: float, duration_s: float,
               request_id: str = "", trace_id: str = "",
               **attrs: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(Span(name=name, start=start,
                                    duration_s=duration_s,
                                    request_id=request_id,
                                    trace_id=trace_id, attrs=dict(attrs)))
        self.histogram(f"{name}_s").observe(duration_s)

    def counter(self, name: str, value: float,
                t: float | None = None) -> None:
        """Record one gauge observation (a point on a counter track)."""
        if not self.enabled:
            return
        with self._lock:
            self._counters.append(
                (time.monotonic() if t is None else t, name, value))

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            if name not in self._hists:
                self._hists[name] = Histogram()
            return self._hists[name]

    def export(self, request_id: str | None = None) -> list[dict[str, Any]]:
        with self._lock:
            spans = list(self._spans)
        if request_id is not None:
            spans = [s for s in spans if s.request_id == request_id]
        return [s.to_dict() for s in spans]

    def export_counters(self) -> list[dict[str, Any]]:
        with self._lock:
            counters = list(self._counters)
        return [{"t": t, "name": name, "value": value}
                for t, name, value in counters]

    def component(self, name: str,
                  clock_offset_s: float = 0.0) -> dict[str, Any]:
        """This tracer's rings as one export_perfetto component entry.
        `clock_offset_s` = (this tracer's clock) - (the merge's reference
        clock); 0 when the caller IS the reference."""
        return {"name": name, "clock_offset_s": clock_offset_s,
                "spans": self.export(), "counters": self.export_counters()}

    def stats(self) -> dict[str, Any]:
        with self._lock:
            hists = dict(self._hists)
        return {name: h.to_dict() for name, h in hists.items()}

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._counters.clear()
            self._hists.clear()


# --------------------------------------------------------------- perfetto

def export_perfetto(components: list[dict[str, Any]],
                    base: float | None = None) -> dict[str, Any]:
    """Merge components' span/counter rings into Chrome trace-event JSON.

    Each component entry is `{"name", "spans", "counters",
    "clock_offset_s"}` (the shape Tracer.component and the host-pipe
    `trace` op produce). `clock_offset_s` is that component's clock minus
    the merge's reference clock (as measured by clock_handshake_offset
    along the hop chain), so `start - clock_offset_s` lands every span on
    ONE reconciled timeline regardless of which process stamped it.

    Output: `{"traceEvents": [...], "displayTimeUnit": "ms"}` —
    loadable in Perfetto (ui.perfetto.dev) or chrome://tracing. One
    "process" row per component (pid = component index), one thread row
    per request within it (named by request id), complete-events ("X")
    for spans, counter events ("C") for gauge tracks. `args` carries
    request_id/trace_id and span attrs, so Perfetto's query/filter box
    isolates one request's end-to-end timeline across all components.
    """
    events: list[dict[str, Any]] = []
    # The reference instant (ts = 0): earliest reconciled stamp across
    # every ring, so all ts values are non-negative offsets from the
    # merge's own beginning.
    if base is None:
        starts = [s["start"] - comp.get("clock_offset_s", 0.0)
                  for comp in components for s in comp.get("spans", [])]
        starts += [c["t"] - comp.get("clock_offset_s", 0.0)
                   for comp in components for c in comp.get("counters", [])]
        base = min(starts) if starts else 0.0

    for pid, comp in enumerate(components, start=1):
        off = comp.get("clock_offset_s", 0.0)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": comp.get("name", "?")}})
        tids: dict[str, int] = {}
        for span in comp.get("spans", []):
            rid = str(span.get("request_id") or "")
            if rid not in tids:
                tids[rid] = len(tids) + 1 if rid else 0
                if rid:
                    events.append({"ph": "M", "name": "thread_name",
                                   "pid": pid, "tid": tids[rid],
                                   "args": {"name": rid}})
            args = {k: v for k, v in span.items()
                    if k not in ("name", "start", "duration_s")
                    and v not in (None, "")}
            events.append({
                "ph": "X", "name": str(span.get("name", "?")), "cat": "span",
                "pid": pid, "tid": tids[rid],
                "ts": round((span["start"] - off - base) * 1e6, 3),
                "dur": round(max(span.get("duration_s", 0.0), 0.0) * 1e6, 3),
                "args": args})
        for c in comp.get("counters", []):
            events.append({
                "ph": "C", "name": str(c["name"]), "pid": pid, "tid": 0,
                "ts": round((c["t"] - off - base) * 1e6, 3),
                "args": {str(c["name"]): c["value"]}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class FlightRecorder:
    """Always-on post-mortem capture over the bounded span rings.

    The rings record continuously (that is the "always-on" part — no
    sampling decision to regret); this class owns the TRIGGER: when a
    request breaches its latency SLO, the engine errors, or an operator
    sends SIGUSR2, the merged last-`window_s` timeline plus a stats()
    snapshot is dumped to one JSON file. Rate-limited so an error storm
    produces one dump per `min_interval_s`, not one per failure.

    The dump file: `{"reason", "written_at", "window_s", "stats",
    "trace": <Chrome trace-event JSON>}` — load `trace` straight into
    Perfetto, read `stats` beside it.
    """

    def __init__(self, out_dir: str, *, window_s: float = 30.0,
                 min_interval_s: float = 30.0,
                 slo_e2e_s: float | None = None) -> None:
        self.out_dir = os.path.expanduser(out_dir)
        self.window_s = window_s
        self.min_interval_s = min_interval_s
        self.slo_e2e_s = slo_e2e_s
        self._last_dump = -1e9
        self._lock = threading.Lock()

    def should_dump(self) -> bool:
        """Rate-limit gate; claims the slot when it grants one."""
        with self._lock:
            now = time.monotonic()
            if now - self._last_dump < self.min_interval_s:
                return False
            self._last_dump = now
            return True

    def dump(self, reason: str, components: list[dict[str, Any]],
             stats: dict[str, Any] | None = None,
             now: float | None = None) -> str:
        """Write one dump (no rate-limit check — pair with should_dump
        for triggered paths; SIGUSR2 calls this directly). Returns the
        file path."""
        now = time.monotonic() if now is None else now
        horizon = now - self.window_s
        recent = []
        for comp in components:
            off = comp.get("clock_offset_s", 0.0)
            spans = [s for s in comp.get("spans", [])
                     if s["start"] - off + s.get("duration_s", 0.0)
                     >= horizon]
            counters = [c for c in comp.get("counters", [])
                        if c["t"] - off >= horizon]
            recent.append({**comp, "spans": spans, "counters": counters})
        payload = {
            "reason": reason,
            "written_at": time.time(),
            "window_s": self.window_s,
            "stats": stats or {},
            "trace": export_perfetto(recent),
        }
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(
            self.out_dir, f"flight_{int(time.time())}_{reason}.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        return path


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace for the enclosed block.

    The TPU-era answer to the reference stack's dormant hypertrace hooks:
    wraps engine work in an XLA/TPU profile (HLO timelines, HBM usage),
    viewable in TensorBoard or Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
