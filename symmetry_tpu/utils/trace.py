"""Tracing and serving metrics (SURVEY §5.1).

The reference ships no tracing of its own — its dependency stack embeds
hypertrace hooks it never enables, and the only counters are wire-level
byte counts on the peer object. Here the equivalents are first-class:

  - Span/Tracer: per-request spans (receive → first-token → end) with a
    bounded in-memory ring, cheap enough to leave on. Each provider owns
    one Tracer instance (provider/provider.py) whose histograms back its
    stats() snapshot.
  - Histogram: log-bucketed latency/throughput distributions with
    percentile estimates — p50/p99 TTFT is the BASELINE.json north-star
    metric, so it must be computable from a running provider, not from
    offline logs.
  - device_trace: on-demand jax.profiler capture for the TPU engine (the
    "trace capture endpoint" of SURVEY §5.1); writes a TensorBoard-loadable
    trace directory.
"""

from __future__ import annotations

import bisect
import contextlib
import math
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator


def _log_buckets(lo: float, hi: float, per_decade: int = 5) -> list[float]:
    n = max(1, int(math.ceil(math.log10(hi / lo) * per_decade)))
    ratio = (hi / lo) ** (1.0 / n)
    return [lo * ratio**i for i in range(n + 1)]


class Histogram:
    """Log-bucketed histogram + bounded raw-sample reservoir.

    Fixed memory, O(log buckets) observe, thread-safe. Default span covers
    0.1 ms .. 100 s — every latency this framework measures.

    Percentiles come from the RESERVOIR, not the buckets: round 4 shipped
    bench captures where provider TTFT p50 == p99 because 5-buckets-per-
    decade (1.58x per bucket) collapsed the whole distribution into one
    bucket — percentiles quoted to milliseconds carried ±26% bucket error.
    Up to `reservoir` observations the percentile is EXACT (every sample
    retained); beyond that, uniform reservoir sampling (Vitter's R) keeps
    an unbiased sample so the estimate degrades gracefully instead of
    quantizing. The buckets stay (20/decade now, ±5.9%) as the bounded
    all-time record behind mean/min/max and cross-checks.
    """

    RESERVOIR = 4096

    def __init__(self, lo: float = 1e-4, hi: float = 100.0,
                 per_decade: int = 20, reservoir: int | None = None) -> None:
        self._edges = _log_buckets(lo, hi, per_decade)
        self._counts = [0] * (len(self._edges) + 1)
        self._lock = threading.Lock()
        self._cap = reservoir if reservoir is not None else self.RESERVOIR
        self._samples: list[float] = []
        # Seeded per-instance PRNG: reservoir eviction must not perturb
        # (or be perturbed by) the global `random` stream, and seeding
        # keeps test runs reproducible.
        self._rng = random.Random(0x5EED)
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def observe(self, value: float) -> None:
        idx = bisect.bisect_right(self._edges, value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            if len(self._samples) < self._cap:
                self._samples.append(value)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._samples[j] = value

    def percentile(self, p: float) -> float | None:
        """p-th percentile (0-100); None when empty. Exact while the
        stream fits the reservoir, an unbiased estimate beyond."""
        with self._lock:
            if not self._samples:
                return None
            xs = sorted(self._samples)
        rank = min(len(xs) - 1, max(0, math.ceil(p / 100.0 * len(xs)) - 1))
        return xs[rank]

    @property
    def mean(self) -> float | None:
        return self.total / self.count if self.count else None

    def to_dict(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


@dataclass(slots=True)
class Span:
    """One completed timed section."""

    name: str
    start: float          # time.monotonic()
    duration_s: float
    request_id: str = ""
    attrs: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "start": self.start,
                "duration_s": self.duration_s,
                "request_id": self.request_id, **self.attrs}


class Tracer:
    """Bounded ring of completed spans + named histograms.

    Instantiate one per component that needs isolated metrics (the
    provider owns one); hot-path cost when disabled is a single attribute
    check.
    """

    def __init__(self, capacity: int = 4096) -> None:
        self.enabled = True
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._hists: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, request_id: str = "",
             **attrs: Any) -> Iterator[dict[str, Any]]:
        """Times the enclosed block. Yields the attrs dict so the block can
        annotate the span (e.g. token counts) before it closes."""
        if not self.enabled:
            yield attrs
            return
        t0 = time.monotonic()
        try:
            yield attrs
        finally:
            self.record(name, t0, time.monotonic() - t0,
                        request_id=request_id, **attrs)

    def record(self, name: str, start: float, duration_s: float,
               request_id: str = "", **attrs: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(Span(name=name, start=start,
                                    duration_s=duration_s,
                                    request_id=request_id, attrs=dict(attrs)))
        self.histogram(f"{name}_s").observe(duration_s)

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            if name not in self._hists:
                self._hists[name] = Histogram()
            return self._hists[name]

    def export(self, request_id: str | None = None) -> list[dict[str, Any]]:
        with self._lock:
            spans = list(self._spans)
        if request_id is not None:
            spans = [s for s in spans if s.request_id == request_id]
        return [s.to_dict() for s in spans]

    def stats(self) -> dict[str, Any]:
        with self._lock:
            hists = dict(self._hists)
        return {name: h.to_dict() for name, h in hists.items()}

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._hists.clear()


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler device trace for the enclosed block.

    The TPU-era answer to the reference stack's dormant hypertrace hooks:
    wraps engine work in an XLA/TPU profile (HLO timelines, HBM usage),
    viewable in TensorBoard or Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
