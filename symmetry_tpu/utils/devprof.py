"""symprof: on-device time attribution via sampling completion probes.

Every instrument before this module measured on HOST clocks: a
scheduler `decode_block` span covers dispatch → host sync, which
conflates device compute, host dispatch overhead, and scheduler idle.
The reckoning round (ROADMAP item 1) needs the split — what fraction of
a steady-state block interval is device time vs host gap — to decide
the W8A16/speculative/disagg knob defaults, and the rounds-3/4
steady-wire gap (~70% of engine-only) is SUSPECTED to be host idle
between device blocks, never yet measured directly.

`DeviceProfiler` is the measurement: a sampling-mode completion probe
around every engine dispatch kind (prefill / chunk / decode_block /
verify / adopt / seed_gather / scatter).

  - On a 1-in-N cadence (`tpu.profile_sample: N`; 0 = off), the probe
    `jax.block_until_ready`s the dispatch's output and timestamps both
    ends: `t_ready - t_begin` is that dispatch's DEVICE DURATION
    (queue + compute, from the moment the host started dispatching).
  - The probed sync drains the device pipeline, so the host time until
    the NEXT dispatch begins is genuine device idle: that interval is
    one DISPATCH GAP sample — the host-side work (emit, detokenize,
    admission bookkeeping) that double-buffering must hide, and the
    steady-wire suspect, finally measured on the device's own terms.
  - Off-mode cost is one attribute load + branch per dispatch (the
    engine guards every hook with `if devprof.enabled:`), the same
    contract as the metrics registry's disabled mode — CI-asserted by
    the overhead guard test. Sampling mode deliberately serializes 1
    dispatch in N (that IS the probe); keep N large enough that the
    tok/s A/B stays within 1% (BASELINE.md Round 15).

Results flow three ways, mirroring every other instrument:

  - `stats()` rides scheduler stats → host stats op → provider
    `engine` block → bench JSON (per-kind device-duration percentiles,
    the dispatch-gap distribution, and `gap_share`).
  - The always-on metrics registry gains `sym_device_*` /
    `sym_dispatch_gap_*` families (tier-labeled through the
    HostOp.METRICS probe like every scheduler family).
  - A dedicated Tracer ring records each probed dispatch as a span
    (name = kind) plus `dispatch_gap` spans, exported by the host's
    `trace` op as a per-host `device` component — the device track
    that renders beside the request spans in the merged Perfetto
    timeline.

`capture_device_profile` is the on-demand heavyweight complement: a
full `jax.profiler` trace (HLO timelines, HBM) for a bounded window,
triggered by the HostOp.PROFILE pipe op (provider wire op, SIGUSR1, or
the SLO burn-rate breach hook alongside the flight recorder) and
dumped as a linkable TensorBoard/Perfetto artifact.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from symmetry_tpu.utils.metrics import METRICS, MetricName
from symmetry_tpu.utils.trace import Histogram, Tracer

# The dispatch kinds the engine wraps. A probe with an unknown kind
# still records (the set is documentation + the smoke's assertion
# vocabulary, not a gate).
DISPATCH_KINDS = ("prefill", "chunk", "decode_block", "verify", "adopt",
                  "seed_gather", "scatter")


class DeviceProfiler:
    """Sampling completion probe over engine dispatches.

    Thread model: `begin`/`probe` run on the ENGINE thread only (the
    engine's single-threaded contract); `stats()` may be called from
    the host's pipe-reader thread, so the shared tallies mutate and
    snapshot under one lock — probes fire 1-in-N, so the critical
    section is nowhere near the hot path's per-dispatch cost.
    """

    def __init__(self, sample_every: int = 0,
                 tracer: Tracer | None = None) -> None:
        self.sample_every = max(0, int(sample_every))
        self.enabled = self.sample_every > 0
        # Bounded span ring: probed dispatches + gaps, exported as the
        # per-host "device" Perfetto component. Smaller than the
        # scheduler ring — probes are 1-in-N by construction.
        self.tracer = tracer if tracer is not None else Tracer(capacity=2048)
        self.tracer.enabled = self.enabled
        self._lock = threading.Lock()
        # PER-KIND dispatch counters (every dispatch, probed or not):
        # the cadence is 1-in-N of EACH kind — a global counter would
        # let frequent decode_blocks absorb every probe slot and leave
        # rare kinds (prefill, verify, scatter) systematically unprobed.
        self._dispatches: dict[str, int] = {}
        self._probes: dict[str, int] = {}
        self._kind_hists: dict[str, Histogram] = {}
        self._gap_hist = Histogram()
        self._device_s = 0.0
        self._gap_s = 0.0
        # Completion stamp of the last probed dispatch; the NEXT
        # begin() closes it into one gap sample. Engine-thread-only.
        self._gap_from: float | None = None
        self._m_dispatch = METRICS.histogram(
            MetricName.DEVICE_DISPATCH,
            "probed device duration per dispatch kind", labels=("kind",))
        self._m_probes = METRICS.counter(
            MetricName.DEVICE_PROBES,
            "completion probes fired per dispatch kind", labels=("kind",))
        self._m_gap = METRICS.histogram(
            MetricName.DISPATCH_GAP,
            "host idle between a probed device completion and the next "
            "dispatch")
        self._m_gap_share = METRICS.gauge(
            MetricName.DISPATCH_GAP_SHARE,
            "dispatch-gap share of probed engine wall "
            "(gap / (gap + device))")

    # ------------------------------------------------------------ hot path

    def begin(self) -> float:
        """Stamp a dispatch's start. Closes the pending gap when the
        PREVIOUS dispatch was probed: the probe drained the pipeline,
        so start - last_ready is genuine device idle. Call on every
        dispatch while enabled (the caller's `if devprof.enabled:`
        guard is the whole off-mode cost)."""
        t = time.monotonic()
        gap_from = self._gap_from
        if gap_from is not None:
            self._gap_from = None
            gap = max(t - gap_from, 0.0)
            self._gap_hist.observe(gap)
            self._m_gap.observe(gap)
            with self._lock:
                self._gap_s += gap
                share = (self._gap_s / (self._gap_s + self._device_s)
                         if (self._gap_s + self._device_s) > 0 else 0.0)
            self._m_gap_share.set(round(share, 4))
            self.tracer.record("dispatch_gap", gap_from, gap)
        return t

    def probe(self, kind: str, value: Any, t0: float) -> None:
        """Maybe-probe a dispatch that began at `t0` (a begin() stamp):
        on the 1-in-N cadence, block until `value` (any jax pytree) is
        device-ready and book t_ready - t0 as the dispatch's device
        duration. Never raises — a probe failure must not fail the
        dispatch it rode."""
        if not self.enabled:
            return  # direct calls with the knob off are no-ops too
        n = self._dispatches.get(kind, 0) + 1
        self._dispatches[kind] = n
        if n % self.sample_every:
            return
        try:
            import jax

            jax.block_until_ready(value)
        except Exception:  # noqa: BLE001 — diagnostics must never fail work
            return
        t1 = time.monotonic()
        dur = max(t1 - t0, 0.0)
        with self._lock:
            self._probes[kind] = self._probes.get(kind, 0) + 1
            hist = self._kind_hists.get(kind)
            if hist is None:
                hist = self._kind_hists[kind] = Histogram()
            self._device_s += dur
        hist.observe(dur)
        self._m_dispatch.observe(dur, kind=kind)
        self._m_probes.inc(kind=kind)
        self.tracer.record(kind, t0, dur)
        self._gap_from = t1

    # ----------------------------------------------------------- snapshots

    def gap_share(self) -> float | None:
        """Gap fraction of probed engine wall, None before any gap
        sample — the steady-state device-idle share headline."""
        with self._lock:
            total = self._gap_s + self._device_s
            if self._gap_hist.count == 0 or total <= 0:
                return None
            return self._gap_s / total

    def stats(self) -> dict[str, Any]:
        """The bench/stats-op block: per-kind device-duration
        percentiles, the dispatch-gap distribution, and the share."""
        with self._lock:
            hists = dict(self._kind_hists)
            probes = dict(self._probes)
            dispatches = dict(self._dispatches)
            device_s, gap_s = self._device_s, self._gap_s
        out: dict[str, Any] = {
            "sample_every": self.sample_every,
            "dispatches": dispatches,
            "probes": probes,
            "device_s": {kind: h.to_dict() for kind, h in hists.items()},
            "device_s_total": round(device_s, 4),
            "dispatch_gap_s": self._gap_hist.to_dict(),
            "dispatch_gap_s_total": round(gap_s, 4),
        }
        share = self.gap_share()
        out["gap_share"] = round(share, 4) if share is not None else None
        return out

    def component(self, name: str = "device") -> dict[str, Any]:
        """The probe span ring as one export_perfetto component — the
        per-host device track beside the request spans."""
        return self.tracer.component(name)


# ------------------------------------------------------ on-demand capture

# One capture at a time per process: jax.profiler refuses concurrent
# traces, and the error it raises mid-serve is worth preventing, not
# catching. The busy flag is guarded by the lock (never held across
# the capture window — the window is seconds long on purpose).
_capture_lock = threading.Lock()
_capture_busy = False


def capture_device_profile(out_dir: str, duration_s: float = 2.0) -> str:
    """Run one bounded jax.profiler capture and return the trace
    directory (TensorBoard-loadable; xplane/trace.json inside are the
    linkable artifacts). Raises RuntimeError when a capture is already
    in progress — callers surface that, never queue behind it."""
    global _capture_busy

    import jax

    with _capture_lock:
        if _capture_busy:
            raise RuntimeError(
                "a device profile capture is already running")
        _capture_busy = True
    try:
        import uuid

        # Timestamp for the operator's eye + a uuid tail for uniqueness:
        # two captures inside the same second must not intermix their
        # artifacts in one directory.
        path = os.path.join(
            os.path.expanduser(out_dir),
            f"profile_{int(time.time())}_{uuid.uuid4().hex[:8]}")
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        try:
            time.sleep(max(0.0, float(duration_s)))
        finally:
            jax.profiler.stop_trace()
        return path
    finally:
        with _capture_lock:
            _capture_busy = False
