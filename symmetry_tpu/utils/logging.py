"""Leveled singleton logger.

Capability parity with the reference logger (reference: src/logger.ts:11-47):
four levels, emoji-prefixed colored console output, global singleton. Unlike
the reference — where only `info` respects the level and warning/error/debug
always print (src/logger.ts:29-44) — every level here is gated consistently,
and output is structured enough to grep.

Structured JSON mode (SYMMETRY_LOG_JSON=1 or set_json_mode(True)): every
record becomes one JSON line on stderr — `{"ts", "t_mono", "level",
"msg"}` plus the ambient `trace_id`/`request_id`/`component` from
log_context(), so log lines correlate with the request-tracing timeline
(utils/trace.py) by the same ids AND the same monotonic clock (`t_mono`
is CLOCK_MONOTONIC — the clock every span and metric ring stamps — so a
log line lands on the merged timeline without wall-clock reconciliation).
The context rides a contextvars.ContextVar: set once around a request's
handling, stamped on every record logged inside it (async tasks inherit
it across awaits; other requests' tasks never see it). `component` names
the subsystem that logged (provider/host/scheduler/slo/...): set a
process-wide default once with set_component(), override per block via
log_context(component=...) — the SLO monitor's breach events log as
component "slo" with the breaching request's trace_id already ambient.
"""

from __future__ import annotations

import contextlib
import contextvars
import enum
import json
import os
import sys
import threading
import time

_log_ctx: contextvars.ContextVar[dict[str, str]] = contextvars.ContextVar(
    "symmetry_log_ctx", default={})


@contextlib.contextmanager
def log_context(trace_id: str = "", request_id: str = "",
                component: str = ""):
    """Stamp trace_id/request_id/component on every record logged inside
    the block (and inside anything it awaits/spawns via context
    inheritance)."""
    ctx = {**_log_ctx.get()}
    if trace_id:
        ctx["trace_id"] = trace_id
    if request_id:
        ctx["request_id"] = request_id
    if component:
        ctx["component"] = component
    token = _log_ctx.set(ctx)
    try:
        yield
    finally:
        _log_ctx.reset(token)


# Process-wide default `component` (e.g. the engine host sets "host"
# once at startup); log_context(component=...) overrides per block.
_default_component = ""


def set_component(name: str) -> None:
    global _default_component
    _default_component = name


class LogLevel(enum.IntEnum):
    ERROR = 0
    WARNING = 1
    INFO = 2
    DEBUG = 3


_COLORS = {
    LogLevel.ERROR: "\x1b[31m",    # red
    LogLevel.WARNING: "\x1b[33m",  # yellow
    LogLevel.INFO: "\x1b[36m",     # cyan
    LogLevel.DEBUG: "\x1b[2m",     # dim
}
_EMOJI = {
    LogLevel.ERROR: "❌",
    LogLevel.WARNING: "⚠️ ",
    LogLevel.INFO: "ℹ️ ",
    LogLevel.DEBUG: "🔍",
}
_RESET = "\x1b[0m"


def _level_from_env(value: str | None) -> LogLevel:
    """Tolerant parse: number or name; bad values fall back to INFO."""
    if not value:
        return LogLevel.INFO
    try:
        return LogLevel(int(value))
    except ValueError:
        pass
    try:
        return LogLevel[value.strip().upper()]
    except KeyError:
        print(f"⚠️  ignoring invalid SYMMETRY_LOG_LEVEL={value!r}", file=sys.stderr)
        return LogLevel.INFO


class Logger:
    """Singleton leveled logger (reference: src/logger.ts:11-24 singleton pattern)."""

    _instance: "Logger | None" = None
    _lock = threading.Lock()

    def __new__(cls) -> "Logger":
        with cls._lock:
            if cls._instance is None:
                cls._instance = super().__new__(cls)
                cls._instance._level = _level_from_env(
                    os.environ.get("SYMMETRY_LOG_LEVEL")
                )
                cls._instance._color = sys.stderr.isatty()
                cls._instance._json = os.environ.get(
                    "SYMMETRY_LOG_JSON", "") not in ("", "0", "false")
            return cls._instance

    def set_log_level(self, level: LogLevel | int) -> None:
        self._level = LogLevel(level)

    def set_json_mode(self, enabled: bool) -> None:
        """One-JSON-object-per-line records with trace/request ids."""
        self._json = bool(enabled)

    @property
    def level(self) -> LogLevel:
        return self._level

    def _emit(self, level: LogLevel, *parts: object) -> None:
        if level > self._level:
            return
        msg = " ".join(str(p) for p in parts)
        if self._json:
            record = {"ts": round(time.time(), 3),
                      # Monotonic stamp: the clock spans/metrics use, so
                      # a log line correlates with the timeline without
                      # wall-clock reconciliation.
                      "t_mono": round(time.monotonic(), 4),
                      "level": level.name.lower(), "msg": msg,
                      **({"component": _default_component}
                         if _default_component else {}),
                      **_log_ctx.get()}
            print(json.dumps(record, ensure_ascii=False), file=sys.stderr,
                  flush=True)
            return
        ts = time.strftime("%H:%M:%S")
        ctx = _log_ctx.get()
        tag = (f" [{ctx['trace_id']}]" if ctx.get("trace_id") else "")
        line = f"{_EMOJI[level]} [{ts}]{tag} {msg}"
        if self._color:
            line = f"{_COLORS[level]}{line}{_RESET}"
        print(line, file=sys.stderr, flush=True)

    def error(self, *parts: object) -> None:
        self._emit(LogLevel.ERROR, *parts)

    def warning(self, *parts: object) -> None:
        self._emit(LogLevel.WARNING, *parts)

    def info(self, *parts: object) -> None:
        self._emit(LogLevel.INFO, *parts)

    def debug(self, *parts: object) -> None:
        self._emit(LogLevel.DEBUG, *parts)


logger = Logger()
