"""Fault injection at named seams — deterministic failure testing.

Every recovery path in this codebase (supervisor respawn, client
failover, busy sheds, deadline sheds) exists because some process can
crash, wedge, or lose a frame at a seam. None of those failures can be
provoked deterministically from outside, so none of the paths were
testable end to end. This module gives each seam a name and lets a test
(or an operator running a chaos drill) arm a fault at it:

    SYMMETRY_FAULTS="host.pipe_write=crash@nth=10;provider.relay=error@p=0.01"

Instrumented seams (grep for `FAULTS.point` / `FAULTS.apoint`):

    host.pipe_write    engine host → provider pipe frame write
    host.pipe_read     engine host command-line read
    backend.dispatch   tpu_native request submit (host pipe or inproc)
    provider.relay     provider → client per-chunk relay
    scheduler.admit    scheduler request admission
    disagg.handoff     prefill-tier handoff frame emit (crash = the
                       prefill host dies with KV built but unshipped;
                       drop_frame = the request silently vanishes)
    disagg.net.send    handoff-link message egress (drop_frame = one
                       link message lost; error/hang = a flaky wire)
    disagg.net.recv    handoff-link message ingress (same actions,
                       receive side)
    disagg.net.drop_link  hit once per handoff transfer attempt, after
                       its first chunk; drop_frame = hard-cut the link
                       mid-handoff (a deterministic cable pull — the
                       decode tier must discard the partial frame,
                       shed in-flight migrations retryable, reconnect)

Actions:

    crash           os._exit(86) — the process dies as if SIGKILLed
                    (no cleanup, no flushed pipes)
    hang(seconds)   block the seam (default 3600 s) — a wedge, not a death
    delay(seconds)  block the seam briefly, then proceed
    error           raise InjectedFault at the seam
    drop_frame      the seam reports "drop this frame" to its caller

Triggers (one per rule; default fires on every hit):

    @once      first hit only
    @nth=N     exactly the Nth hit of that seam (1-based), once
    @every=N   every Nth hit
    @p=X       each hit independently with probability X

Configuration merges from the SYMMETRY_FAULTS environment variable (read
once at import — inherited by subprocesses, which is how a fault reaches
the engine host) and from a provider-config `faults:` mapping
(seam → spec string), loaded by the host and provider at startup.

Unconfigured, the injector is a no-op: every call site guards on
`FAULTS.enabled` (one attribute read), and `point()` itself returns
after one boolean check — CI asserts the overhead (tools/chaos_smoke.py).
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from dataclasses import dataclass, field


class InjectedFault(RuntimeError):
    """Raised by an `error` fault action at an instrumented seam."""


_ACTION_RE = re.compile(
    r"^(crash|hang|delay|error|drop_frame)(?:\(([^)]*)\))?$")

_DEFAULT_HANG_S = 3600.0


@dataclass
class FaultRule:
    """One armed fault: seam + action + trigger, with hit accounting."""

    seam: str
    kind: str                  # crash | hang | delay | error | drop_frame
    seconds: float = 0.0       # hang/delay duration
    message: str = ""          # error message override
    trigger: str = "always"    # always | once | nth | every | p
    n: int = 1                 # nth / every operand
    prob: float = 1.0          # p operand
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def should_fire(self) -> bool:
        """Record one hit; report whether the action fires on it.
        Caller holds the injector lock."""
        self.hits += 1
        if self.trigger == "once":
            ok = self.fired == 0
        elif self.trigger == "nth":
            ok = self.hits == self.n
        elif self.trigger == "every":
            ok = self.hits % self.n == 0
        elif self.trigger == "p":
            ok = random.random() < self.prob
        else:
            ok = True
        if ok:
            self.fired += 1
        return ok


def parse_rule(seam: str, spec: str) -> FaultRule:
    """One rule from its spec string, e.g. ``hang(30)@nth=4``."""
    seam = seam.strip()
    spec = spec.strip()
    if not seam:
        raise ValueError(f"fault rule missing seam name: {spec!r}")
    action, _, trig = spec.partition("@")
    m = _ACTION_RE.match(action.strip())
    if m is None:
        raise ValueError(
            f"bad fault action {action!r} for seam {seam!r} "
            f"(want crash|hang(s)|delay(s)|error(msg)|drop_frame)")
    kind, arg = m.group(1), m.group(2)
    rule = FaultRule(seam=seam, kind=kind)
    if kind in ("hang", "delay"):
        rule.seconds = float(arg) if arg else (
            _DEFAULT_HANG_S if kind == "hang" else 0.0)
        if kind == "delay" and not arg:
            raise ValueError(f"delay requires a duration: {spec!r}")
    elif kind == "error" and arg:
        rule.message = arg
    elif arg:
        raise ValueError(f"action {kind!r} takes no argument: {spec!r}")
    trig = trig.strip()
    if trig:
        if trig == "once":
            rule.trigger = "once"
        elif trig.startswith("nth="):
            rule.trigger, rule.n = "nth", int(trig[4:])
        elif trig.startswith("every="):
            rule.trigger, rule.n = "every", int(trig[6:])
        elif trig.startswith("p="):
            rule.trigger, rule.prob = "p", float(trig[2:])
        else:
            raise ValueError(
                f"bad fault trigger {trig!r} for seam {seam!r} "
                f"(want once | nth=N | every=N | p=X)")
        if rule.trigger in ("nth", "every") and rule.n < 1:
            raise ValueError(f"trigger operand must be >= 1: {spec!r}")
    return rule


class FaultInjector:
    """Process-global registry of armed faults, fired at named seams."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rules: dict[str, list[FaultRule]] = {}
        # The hot-path guard: call sites read this one attribute before
        # paying for a method call. Only load()/clear() write it.
        self.enabled = False

    def load(self, spec) -> None:
        """Arm rules from a spec. Accepts the env-string form
        (``seam=action@trigger;seam=...``), a mapping of seam → spec
        string (or list of spec strings) — the provider-config `faults:`
        shape — or None/"" (no-op). Merges with existing rules."""
        if not spec:
            return
        rules: list[FaultRule] = []
        if isinstance(spec, str):
            for entry in spec.split(";"):
                entry = entry.strip()
                if not entry:
                    continue
                seam, sep, action = entry.partition("=")
                if not sep:
                    raise ValueError(f"bad fault entry {entry!r} "
                                     f"(want seam=action[@trigger])")
                rules.append(parse_rule(seam, action))
        elif isinstance(spec, dict):
            for seam, val in spec.items():
                for one in (val if isinstance(val, (list, tuple)) else [val]):
                    rules.append(parse_rule(str(seam), str(one)))
        else:
            raise ValueError(f"fault spec must be str or mapping, "
                             f"got {type(spec).__name__}")
        with self._lock:
            for rule in rules:
                self._rules.setdefault(rule.seam, []).append(rule)
            self.enabled = bool(self._rules)

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
            self.enabled = False

    def rules(self) -> list[FaultRule]:
        with self._lock:
            return [r for lst in self._rules.values() for r in lst]

    def counters(self) -> dict[str, dict[str, int]]:
        """Per-seam hit/fired accounting (ride-along for stats replies)."""
        with self._lock:
            return {seam: {"hits": sum(r.hits for r in lst),
                           "fired": sum(r.fired for r in lst)}
                    for seam, lst in self._rules.items()}

    def fire(self, seam: str) -> FaultRule | None:
        """Record a hit at `seam`; return the rule whose action fires,
        if any. First armed rule wins — later rules on the same seam
        still record the HIT, but their trigger budget (@once/@nth) is
        only consumed when they are actually selected, so `fired`
        counters report applied actions, nothing else."""
        if not self.enabled:
            return None
        with self._lock:
            fired = None
            for rule in self._rules.get(seam, ()):
                if fired is None:
                    if rule.should_fire():
                        fired = rule
                else:
                    rule.hits += 1
            return fired

    # ------------------------------------------------------------ seams

    def point(self, seam: str) -> bool:
        """Synchronous seam: perform the armed action, if any. Returns
        True when the caller should DROP the current frame/request
        (drop_frame action), False otherwise. crash exits the process;
        hang/delay block the calling thread; error raises InjectedFault."""
        if not self.enabled:
            return False
        rule = self.fire(seam)
        if rule is None:
            return False
        return self._apply(rule, time.sleep)

    async def apoint(self, seam: str) -> bool:
        """Async seam: like point(), but hang/delay await the event loop's
        clock instead of blocking the whole loop."""
        if not self.enabled:
            return False
        rule = self.fire(seam)
        if rule is None:
            return False
        if rule.kind in ("hang", "delay"):
            import asyncio

            await asyncio.sleep(rule.seconds)
            return False
        return self._apply(rule, time.sleep)

    def _apply(self, rule: FaultRule, sleep) -> bool:
        if rule.kind == "crash":
            # As close to a real crash as Python offers: no atexit, no
            # finally blocks, no flushed buffers.
            os._exit(86)
        if rule.kind in ("hang", "delay"):
            sleep(rule.seconds)
            return False
        if rule.kind == "error":
            raise InjectedFault(
                rule.message or f"injected fault at {rule.seam}")
        return True  # drop_frame


FAULTS = FaultInjector()
FAULTS.load(os.environ.get("SYMMETRY_FAULTS"))
