"""Mixture-of-experts MLP (mixtral family) — dense-mixture, TPU-first.

The reference has no model code at all (SURVEY §0); MoE enters through the
framework's model-family coverage (mixtral-8x7b preset, llama.py) and the
`expert` mesh axis (SURVEY §2.3: expert parallelism "only if MoE models
are added" — they are).

Design: DENSE mixture. Every expert processes every token; the top-k
router gates (zeros outside the selected experts) weight the combine. Why
this is the TPU-right shape for serving:

  - A serving batch of B slots × top-2 routing touches essentially every
    expert every step, so all expert weights stream from HBM regardless —
    the decode step stays bandwidth-bound and skipping compute for
    unselected (token, expert) pairs saves no HBM traffic.
  - The expert dim becomes a leading batch dim of ONE big dot_general per
    projection — the MXU sees [experts] × [tokens, embed] @ [embed, ffn]
    batched matmuls, no gathers, no ragged dispatch, no recompiles.
  - Sharding: experts map to the `expert` mesh axis and each expert's ffn
    dim to `model` (parallel/sharding.py rules); XLA derives the combine
    all-reduce from the shardings, exactly like the dense-MLP TP path.

Capacity-factor dispatch (real token→expert all-to-all) becomes worthwhile
at prefill scale on big meshes; the routing math here (softmax-over-top-k,
renormalized) matches mixtral so that upgrade is drop-in.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.quant import QuantizedTensor


def qmatmul_experts(x: jnp.ndarray, w) -> jnp.ndarray:
    """[B, S, D] @ per-expert [X, D, F] -> [B, S, X, F].

    QuantizedTensor experts keep the int8 payload as the dot operand (no
    bf16 materialization — same rule as ops/quant.py qmatmul); per-column
    scales [X, F] apply to the f32 accumulator."""
    if isinstance(w, QuantizedTensor):
        y = jax.lax.dot_general(
            x, w.q,
            dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [B, S, X, F]
        return (y * w.scale).astype(x.dtype)
    return jnp.einsum("bsd,xdf->bsxf", x, w)


def route_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Router logits [B, S, X] -> dense gates [B, S, X]: softmax over the
    top-k logits (mixtral semantics: normalize AFTER selection), zeros
    elsewhere. Static-shape: one_hot scatter, no gathers."""
    top_vals, top_idx = jax.lax.top_k(logits, k)          # [B, S, k]
    probs = jax.nn.softmax(top_vals, axis=-1)
    onehot = jax.nn.one_hot(top_idx, logits.shape[-1],
                            dtype=probs.dtype)            # [B, S, k, X]
    return jnp.einsum("bsk,bskx->bsx", probs, onehot)


def moe_mlp(x: jnp.ndarray, lp: dict, config) -> jnp.ndarray:
    """Dense-mixture MoE FFN: [B, S, E] -> [B, S, E]."""
    gates = route_top_k(
        jnp.asarray(x @ lp["router"], jnp.float32),
        config.num_experts_per_tok).astype(x.dtype)       # [B, S, X]
    h = jax.nn.silu(qmatmul_experts(x, lp["wg"])) * qmatmul_experts(
        x, lp["wu"])                                      # [B, S, X, F]
    # Per-expert down-projection then gated combine over experts.
    wd = lp["wd"]
    if isinstance(wd, QuantizedTensor):
        y = jax.lax.dot_general(
            h, wd.q,
            dimension_numbers=(((3,), (1,)), ((2,), (0,))),
            preferred_element_type=jnp.float32,
        )  # batch over experts: [X, B, S, E]
        y = (y * wd.scale[:, None, None, :]).astype(x.dtype)
        y = jnp.moveaxis(y, 0, 2)                         # [B, S, X, E]
    else:
        y = jnp.einsum("bsxf,xfe->bsxe", h, wd)
    return jnp.einsum("bsxe,bsx->bse", y, gates)
