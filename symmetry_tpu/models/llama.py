"""Llama-family decoder (llama 3.x, mistral, any HF-llama-shaped LM).

Design (TPU-first, not a port — the reference has no model code to port):

  - Params are a plain pytree; `forward` is a pure function of
    (params, tokens, positions, cache). Everything jits.
  - All decoder layers are STACKED along a leading `layers` dim and executed
    with `lax.scan`: compile time is O(1) in depth (llama3-70b is 80 layers;
    unrolled tracing would take minutes and bloat the executable).
  - Projection weights stay fused 2-D ([embed, heads*head_dim]) so each layer
    is a handful of large matmuls the MXU tiles well, with logical axes
    mapped to the mesh by parallel/sharding.py (megatron-style TP by
    default — XLA derives the per-layer collectives from the shardings).
  - One forward serves prefill AND decode: masking is by absolute position
    (ops/attention.py), cache writes are scatters at per-sample positions,
    so a continuous batch of ragged requests runs at static shape.

HF weight compatibility (BASELINE.json north star loads HF safetensors):
tensor layout/naming map in `HF_LAYER_MAP` + `convert_hf_params`
(engine/weights.py does the streaming file IO).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.attention import gqa_attention
from symmetry_tpu.ops.norm import rms_norm
from symmetry_tpu.ops.quant import QuantizedTensor, qmatmul, quantize_tree
from symmetry_tpu.ops.rope import apply_rope


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    head_dim: int | None = None          # defaults to hidden//heads
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    sliding_window: int | None = None    # mistral-v0.1 style local attention
    attention_bias: bool = False         # qwen2-style QKV projection biases
    max_position: int = 8192
    # gemma family: gelu-tanh GeGLU, RMSNorm scale stored as (weight - 1),
    # and embeddings multiplied by sqrt(hidden) at lookup
    hidden_act: str = "silu"             # "silu" | "gelu_tanh"
    norm_plus_one: bool = False
    scale_embed: bool = False

    @property
    def dim_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.dim_per_head

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.dim_per_head


@dataclass(frozen=True)
class MoEConfig(ModelConfig):
    """Mixture-of-experts variant (mixtral family): the MLP becomes
    num_experts parallel FFNs with top-k routing (models/moe.py)."""

    num_experts: int = 8
    num_experts_per_tok: int = 2
    # Prefill token-dispatch capacity (models/moe.py); None = the module
    # default. Set >= num_experts / num_experts_per_tok for zero drops.
    moe_capacity_factor: float | None = None


# Named presets; sizes from the public HF configs of each model family.
PRESETS: dict[str, ModelConfig] = {
    # test-scale models (CPU-fast, exercised by the suite)
    "tiny": ModelConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128, rope_theta=10000.0,
        max_position=512,
    ),
    "tiny-mha": ModelConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=4, intermediate_size=128, rope_theta=10000.0,
        max_position=512,
    ),
    # production targets (BASELINE.json configs 2-5)
    "llama3-8b": ModelConfig(
        vocab_size=128256, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, rope_theta=500000.0,
    ),
    "llama3-70b": ModelConfig(
        vocab_size=128256, hidden_size=8192, num_layers=80, num_heads=64,
        num_kv_heads=8, intermediate_size=28672, rope_theta=500000.0,
    ),
    "llama3.2-1b": ModelConfig(
        vocab_size=128256, hidden_size=2048, num_layers=16, num_heads=32,
        num_kv_heads=8, intermediate_size=8192, rope_theta=500000.0,
        tie_embeddings=True,
    ),
    "mistral-7b": ModelConfig(
        vocab_size=32768, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, rope_theta=1000000.0,
    ),
    "tiny-moe": MoEConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128, rope_theta=10000.0,
        max_position=512, num_experts=4, num_experts_per_tok=2,
    ),
    "mixtral-8x7b": MoEConfig(
        vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, rope_theta=1000000.0,
        num_experts=8, num_experts_per_tok=2,
    ),
    "gemma-7b": ModelConfig(
        vocab_size=256000, hidden_size=3072, num_layers=28, num_heads=16,
        num_kv_heads=16, intermediate_size=24576, head_dim=256,
        rope_theta=10000.0, rms_eps=1e-6, tie_embeddings=True,
        hidden_act="gelu_tanh", norm_plus_one=True, scale_embed=True,
    ),
    "gemma-2b": ModelConfig(
        vocab_size=256000, hidden_size=2048, num_layers=18, num_heads=8,
        num_kv_heads=1, intermediate_size=16384, head_dim=256,
        rope_theta=10000.0, rms_eps=1e-6, tie_embeddings=True,
        hidden_act="gelu_tanh", norm_plus_one=True, scale_embed=True,
    ),
    "tiny-gemma": ModelConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128, head_dim=16,
        rope_theta=10000.0, max_position=512, tie_embeddings=True,
        hidden_act="gelu_tanh", norm_plus_one=True, scale_embed=True,
    ),
    "qwen2-7b": ModelConfig(
        vocab_size=152064, hidden_size=3584, num_layers=28, num_heads=28,
        num_kv_heads=4, intermediate_size=18944, rope_theta=1000000.0,
        rms_eps=1e-6, attention_bias=True,
    ),
    "tiny-qwen": ModelConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128, rope_theta=10000.0,
        max_position=512, attention_bias=True,
    ),
}


def preset(name: str) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name]


class KVCache(NamedTuple):
    """Static-shape KV cache: [layers, batch, capacity, kv_heads, head_dim].

    With quantized=True at init, k/v hold int8 payloads and k_scale/v_scale
    hold the per-(layer, slot, kv_head, position) f32 dequant scales
    (ops/quant.py quantize_kv) — [layers, batch, kv_heads, capacity].
    Position is the MINOR scale dim on purpose: with kv_heads (8) minor the
    arrays would tile-pad 16x in HBM the moment a Pallas kernel takes them
    as operands. The scale planes are head_dim× smaller than the payload,
    so the decode-step cache read drops to ~half of bf16.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    lengths: jnp.ndarray  # [batch] int32: valid entries per slot
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(
    config: ModelConfig, batch: int, capacity: int, dtype=jnp.bfloat16,
    *, quantized: bool = False,
) -> KVCache:
    shape = (config.num_layers, batch, capacity, config.num_kv_heads,
             config.dim_per_head)
    if quantized:
        scale_shape = (config.num_layers, batch, config.num_kv_heads,
                       capacity)
        return KVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            lengths=jnp.zeros((batch,), jnp.int32),
            k_scale=jnp.zeros(scale_shape, jnp.float32),
            v_scale=jnp.zeros(scale_shape, jnp.float32),
        )
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Parameters


def init_params(config: ModelConfig, key: jax.Array, dtype=jnp.bfloat16,
                *, quantize: bool = False) -> dict:
    """Random init (scaled normal). Real serving loads HF weights instead.

    quantize=True materializes QUANT_KEYS leaves as int8 directly — the
    whole random-init→scale→quantize pipeline for a leaf runs as ONE
    compiled program (ops/quant.py make_leaf), so no full-precision copy of
    a leaf ever lands in HBM beyond that program's fused temporaries. That
    is what lets an 8B-parameter model initialize on a 16 GB chip.
    """
    c = config
    keys = iter(jax.random.split(key, 16))

    from symmetry_tpu.ops.quant import make_leaf

    def dense(k, shape, scale=None, name=None):
        scale = scale if scale is not None else shape[0] ** -0.5
        return make_leaf(k, shape, scale, dtype,
                         quantized=quantize and name in QUANT_KEYS)

    L, E, F = c.num_layers, c.hidden_size, c.intermediate_size
    n_exp = getattr(c, "num_experts", 0)
    # MoE: FFN weights gain a leading experts dim; the router stays dense
    # (it is contracted per token, tiny, and its logits feed a top-k).
    ffn = (L, n_exp, E, F) if n_exp else (L, E, F)
    ffn_d = (L, n_exp, F, E) if n_exp else (L, F, E)
    params = {
        "embed": dense(next(keys), (c.vocab_size, E), scale=0.02),
        "layers": {
            "attn_norm": jnp.ones((L, E), dtype),
            "mlp_norm": jnp.ones((L, E), dtype),
            "wq": dense(next(keys), (L, E, c.q_dim), name="wq"),
            "wk": dense(next(keys), (L, E, c.kv_dim), name="wk"),
            "wv": dense(next(keys), (L, E, c.kv_dim), name="wv"),
            "wo": dense(next(keys), (L, c.q_dim, E), name="wo"),
            "wg": dense(next(keys), ffn, name="wg"),
            "wu": dense(next(keys), ffn, name="wu"),
            "wd": dense(next(keys), ffn_d, name="wd"),
        },
        "final_norm": jnp.ones((E,), dtype),
    }
    if n_exp:
        params["layers"]["router"] = dense(next(keys), (L, E, n_exp))
    if c.attention_bias:
        # qwen2: biases on q/k/v projections only (not o/mlp)
        params["layers"]["bq"] = jnp.zeros((L, c.q_dim), dtype)
        params["layers"]["bk"] = jnp.zeros((L, c.kv_dim), dtype)
        params["layers"]["bv"] = jnp.zeros((L, c.kv_dim), dtype)
    if not c.tie_embeddings:
        params["lm_head"] = dense(next(keys), (E, c.vocab_size), scale=0.02,
                                  name="lm_head")
    return params


def param_logical_axes(config: ModelConfig) -> dict:
    """Pytree of logical-axis tuples, same structure as init_params output."""
    moe = bool(getattr(config, "num_experts", 0))
    ffn = (("layers", "experts", "embed", "mlp") if moe
           else ("layers", "embed", "mlp"))
    ffn_d = (("layers", "experts", "mlp", "embed") if moe
             else ("layers", "mlp", "embed"))
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "mlp_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "wg": ffn,
            "wu": ffn,
            "wd": ffn_d,
        },
        "final_norm": ("embed",),
    }
    if moe:
        axes["layers"]["router"] = ("layers", "embed", None)
    if config.attention_bias:
        axes["layers"]["bq"] = ("layers", "heads")
        axes["layers"]["bk"] = ("layers", "kv_heads")
        axes["layers"]["bv"] = ("layers", "kv_heads")
    if not config.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def cache_logical_axes(*, quantized: bool = False) -> KVCache:
    kv = ("layers", "batch", "cache_seq", "kv_heads", "head_dim")
    sc = ("layers", "batch", "kv_heads", "cache_seq") if quantized else None
    return KVCache(k=kv, v=kv, lengths=("batch",), k_scale=sc, v_scale=sc)


# ---------------------------------------------------------------------------
# Forward


def _layer(
    h: jnp.ndarray,             # [B, S, E]
    lp: dict,                   # one layer's params (leading L dim stripped)
    cache: KVCache,             # FULL [L, B, T, K, D] cache (lengths unused)
    layer: jnp.ndarray,         # scalar int32 layer index
    positions: jnp.ndarray,     # [B, S]
    kv_valid: jnp.ndarray,      # [B] cache length AFTER this call's writes
    seq_lens: jnp.ndarray,      # [B] valid tokens in this call's input
    config: ModelConfig,
    prefill_flash: bool,        # static: flash self-attention (fresh cache)
    ring_mesh=None,             # static: Mesh => sequence-parallel prefill
    sp_mode: str = "ring",      # static: "ring" | "ulysses" (SURVEY §5.7)
    kv_append_ok: bool = True,  # static: False for sharded caches (TP/PP)
) -> tuple[jnp.ndarray, KVCache]:
    B, S, E = h.shape
    D, nq, nkv = config.dim_per_head, config.num_heads, config.num_kv_heads

    x = rms_norm(h, _norm_w(lp["attn_norm"], config), config.rms_eps)
    q = qmatmul(x, lp["wq"])
    k = qmatmul(x, lp["wk"])
    v = qmatmul(x, lp["wv"])
    if config.attention_bias:  # qwen2 family
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, S, nq, D)
    k = k.reshape(B, S, nkv, D)
    v = v.reshape(B, S, nkv, D)
    q = apply_rope(q, positions, config.rope_theta)
    k = apply_rope(k, positions, config.rope_theta)

    # Scatter the new K/V straight into the full cache at (layer, batch,
    # position) — an in-place row write on the scan carry; a per-layer
    # slice-out/slice-in would stream the whole layer slice through HBM.
    # Padded tail tokens write garbage past kv_valid — never read,
    # overwritten later. Quantized caches write int8 payload + f32 scales.
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    l_idx = jnp.full((B, S), layer, jnp.int32)
    if cache.quantized:
        from symmetry_tpu.ops import kv_append as kva
        from symmetry_tpu.ops.quant import quantize_kv

        if (S == 1 and kv_append_ok
                and kva.supports(cache.k.shape[2], D,
                                 jax.default_backend(),
                                 sharded=False)):
            # Decode: one fused Pallas call quantizes and writes the row
            # in place — the XLA path below costs ~14 kernels/layer incl.
            # a full-plane select on the position-minor scale planes
            # (ops/kv_append.py; round-4 decode-floor work).
            ck, cv, ks_, vs_ = kva.kv_append(
                cache.k, cache.v, cache.k_scale, cache.v_scale,
                k[:, 0], v[:, 0], layer, positions[:, 0])
            cache = cache._replace(k=ck, v=cv, k_scale=ks_, v_scale=vs_)
        else:
            kq, ks = quantize_kv(k)  # ks [B, S, K]
            vq, vs = quantize_kv(v)
            # Scale planes are [L, B, K, T] (position minor, see KVCache):
            # the mixed advanced/slice index puts the advanced dims (B, S)
            # in front, matching the [B, S, K] scale values.
            cache = cache._replace(
                k=cache.k.at[l_idx, b_idx, positions].set(kq),
                v=cache.v.at[l_idx, b_idx, positions].set(vq),
                k_scale=cache.k_scale.at[l_idx, b_idx, :, positions].set(ks),
                v_scale=cache.v_scale.at[l_idx, b_idx, :, positions].set(vs),
            )
    else:
        cache = cache._replace(
            k=cache.k.at[l_idx, b_idx, positions].set(k.astype(cache.k.dtype)),
            v=cache.v.at[l_idx, b_idx, positions].set(v.astype(cache.v.dtype)),
        )

    if ring_mesh is not None:
        # Long-context prefill: sequence sharded over the `context` mesh
        # axis — K/V blocks rotating on ICI (parallel/ring.py), or one
        # all-to-all head scatter when heads divide the shard count
        # (parallel/ulysses.py).
        if sp_mode == "ulysses":
            from symmetry_tpu.parallel.ulysses import ulysses_attention

            attn = ulysses_attention(q, k, v, seq_lens, ring_mesh)
        else:
            from symmetry_tpu.parallel.ring import ring_attention

            attn = ring_attention(q, k, v, seq_lens, ring_mesh)
    elif prefill_flash:
        # Prefill-from-empty: attention is over this call's own K/V — the
        # Pallas kernel streams K/V blocks through VMEM instead of
        # materializing [H, S, S] scores (ops/flash.py); the cache slice is
        # never read back. Sliding-window models restrict the kernel's
        # block range to the window.
        from symmetry_tpu.ops.flash import flash_prefill

        attn = flash_prefill(q, k, v, seq_lens,
                             window=config.sliding_window,
                             interpret=jax.default_backend() != "tpu")
    else:
        from symmetry_tpu.ops import decode_attention as da

        if S == 1 and da.supports(config, cache.k.shape[2],
                                  jax.default_backend()):
            # Single-position decode on TPU: the Pallas kernel reads only
            # each slot's occupied KV prefix (per-slot block skipping); the
            # full cache is its operand, layer selection happens in the
            # kernel's block addressing (ops/decode_attention.py).
            attn = da.decode_attention(
                q[:, 0], cache.k, cache.v, layer, kv_valid,
                k_scale=cache.k_scale if cache.quantized else None,
                v_scale=cache.v_scale if cache.quantized else None,
                window=config.sliding_window,
                interpret=jax.default_backend() != "tpu")[:, None]
        else:
            def at_layer(arr):
                return jax.lax.dynamic_index_in_dim(arr, layer, 0,
                                                    keepdims=False)

            attn = gqa_attention(
                q, at_layer(cache.k), at_layer(cache.v), positions, kv_valid,
                sliding_window=config.sliding_window,
                k_scale=at_layer(cache.k_scale) if cache.quantized else None,
                v_scale=at_layer(cache.v_scale) if cache.quantized else None)
    h = h + qmatmul(attn.reshape(B, S, nq * D), lp["wo"])

    x = rms_norm(h, _norm_w(lp["mlp_norm"], config), config.rms_eps)
    if "router" in lp:
        from symmetry_tpu.models.moe import moe_mlp

        h = h + moe_mlp(x, lp, config)
    else:
        h = h + qmatmul(_act(qmatmul(x, lp["wg"]), config)
                        * qmatmul(x, lp["wu"]), lp["wd"])
    return h, cache


def _act(x: jnp.ndarray, config: ModelConfig) -> jnp.ndarray:
    """Gated-MLP activation: silu (llama/mistral/qwen) or tanh-approx gelu
    (gemma's GeGLU)."""
    if config.hidden_act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _norm_w(w: jnp.ndarray, config: ModelConfig) -> jnp.ndarray:
    """Gemma stores RMSNorm scale as (weight - 1): the model applies
    (1 + w). The add runs in float32 — HF GemmaRMSNorm computes
    (1.0 + weight.float()), and doing it in a bf16 checkpoint dtype would
    round the multiplier at every one of the model's norm sites. rms_norm
    upcasts anyway, so this costs nothing."""
    if not config.norm_plus_one:
        return w
    return w.astype(jnp.float32) + 1.0


def forward_hidden(
    params: dict,
    config: ModelConfig,
    tokens: jnp.ndarray,      # [B, S] int32
    cache: KVCache,           # lengths[b] = tokens already in cache for slot b
    seq_lens: jnp.ndarray | None = None,  # [B] valid tokens in `tokens`; None = all S
    *,
    prefill_flash: bool = False,  # static: caller guarantees cache is empty
    ring_mesh=None,               # static: context-parallel prefill mesh
    sp_mode: str = "ring",        # static: "ring" | "ulysses"
    kv_append_ok: bool = True,    # static: False when the cache is sharded
) -> tuple[jnp.ndarray, KVCache]:
    """Decoder trunk: returns (final-norm hidden states [B, S, E], cache).

    Split from the LM head so prefill can project only the last valid
    position — at 128k vocab the head matmul over a full padded bucket would
    dominate prefill cost.

    prefill_flash=True routes attention through the Pallas flash kernel.
    VALID ONLY when cache.lengths are all zero (engine prefill's case) —
    both fast paths attend to this call's own K/V, not the cache.
    ring_mesh additionally shards the sequence over the mesh's `context`
    axis; it requires prefill_flash's empty-cache contract and S divisible
    by the shard count. sp_mode picks the scheme: "ring" rotates K/V
    blocks (parallel/ring.py, any head count), "ulysses" head-scatters via
    one all-to-all (parallel/ulysses.py, needs kv_heads % shards == 0).
    Sliding-window models (mistral-v0.1) use the window-bounded flash
    kernel for prefill. The ring/ulysses schemes do not support windows:
    with ring_mesh set, a sliding-window model runs the (non-sequence-
    parallel) flash kernel instead — callers needing SP for windowed
    models must shard some other way.
    """
    B, S = tokens.shape
    if seq_lens is None:
        seq_lens = jnp.full((B,), S, jnp.int32)
    positions = cache.lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    kv_valid = cache.lengths + seq_lens
    if ring_mesh is not None and not prefill_flash:
        # Ring q/kv positions start at 0 and ignore cached entries — only
        # the prefill-from-empty contract makes that correct. Fail loudly
        # rather than silently mis-attend on a continuation call.
        raise ValueError("ring_mesh requires prefill_flash=True "
                         "(prefill-from-empty contract)")
    use_ring = ring_mesh if (ring_mesh is not None and S > 1
                             and config.sliding_window is None) else None
    # Flash prefill handles sliding windows natively (window-bounded block
    # range); only the ring path still requires global attention.
    use_flash = prefill_flash and use_ring is None and S > 1

    n_stacked = jax.tree.leaves(params["layers"])[0].shape[0]
    if n_stacked != config.num_layers:
        # A config/checkpoint depth mismatch must fail loudly: the cache is
        # sized by config, and out-of-bounds scatter/gather on the extra
        # layers would be silently dropped/clamped instead of erroring.
        raise ValueError(f"params carry {n_stacked} stacked layers but "
                         f"config.num_layers = {config.num_layers}")
    h = jnp.take(params["embed"], tokens, axis=0)
    if config.scale_embed:
        # gemma: embeddings scaled by sqrt(hidden) at lookup, normalizer
        # cast to the activation dtype (HF modeling_gemma semantics)
        h = h * jnp.asarray(config.hidden_size ** 0.5, h.dtype)
    h, new_cache = run_layers(params["layers"], h, cache, positions,
                              kv_valid, seq_lens, config,
                              use_flash=use_flash, use_ring=use_ring,
                              sp_mode=sp_mode, kv_append_ok=kv_append_ok)
    h = rms_norm(h, _norm_w(params["final_norm"], config), config.rms_eps)
    return h, new_cache._replace(lengths=kv_valid)


def run_layers(
    layers_params: dict,
    h: jnp.ndarray,
    cache: KVCache,            # leading layer dim == layers_params' leading dim
    positions: jnp.ndarray,
    kv_valid: jnp.ndarray,
    seq_lens: jnp.ndarray,
    config: ModelConfig,
    *,
    use_flash: bool = False,
    use_ring=None,
    sp_mode: str = "ring",
    kv_append_ok: bool = True,
) -> tuple[jnp.ndarray, KVCache]:
    """Scan a stack of decoder layers over `h`. Factored out of
    forward_hidden so pipeline parallelism (parallel/pipeline.py) can run a
    STAGE'S local slice of layers against its local cache shard — layer
    indices inside are local to the stack passed in, which is exactly what
    the per-stage cache expects."""

    def body(carry, xs):
        # The cache rides the CARRY, scatter-updated in place: scan xs/ys
        # would stream the full [L, B, T, K, D] arrays through HBM every
        # forward — at decode that re-writes ~0.5 GB per token.
        h, c = carry
        lp, l = xs
        h, c = _layer(h, lp, c, l, positions, kv_valid,
                      seq_lens, config, use_flash, ring_mesh=use_ring,
                      sp_mode=sp_mode, kv_append_ok=kv_append_ok)
        return (h, c), None

    n_layers = jax.tree.leaves(layers_params)[0].shape[0]
    (h, new_cache), _ = jax.lax.scan(
        body, (h, cache),
        (layers_params, jnp.arange(n_layers, dtype=jnp.int32)))
    return h, new_cache


def logits_from_hidden(params: dict, config: ModelConfig,
                       h: jnp.ndarray) -> jnp.ndarray:
    """LM head: [B, S, E] hidden -> [B, S, vocab] float32 logits."""
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    return qmatmul(h, head).astype(jnp.float32)


# Weights eligible for int8 quantization (all the large matmuls; the
# embedding stays dense — it is gathered, not contracted).
QUANT_KEYS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd", "lm_head")


def quantize_params(params: dict) -> dict:
    """In-place int8 quantization of all QUANT_KEYS leaves (ops/quant.py)."""
    return quantize_tree(params, QUANT_KEYS)


def pack_params(params: dict, *, config: ModelConfig | None = None,
                mesh=None, rules: dict | None = None,
                report: list | None = None) -> dict:
    """In-place tile-packing of quantized QUANT_KEYS leaves into the
    W8A16 fused-dequant kernel layout (`tpu.fused_dequant`; ops/quant.py
    pack_tree). Layout is routing: qmatmul sends PackedQuantizedTensor
    leaves through the Pallas kernel and leaves everything else on the
    mixed dot, so per-leaf tileability fallback is automatic.

    With `mesh` (+ `config`, required to resolve each leaf's logical
    axes), packing happens AFTER the sharding decision: every leaf's
    contraction/output mesh axes come from the SAME logical-axis tree +
    rules the dense/int8 placement used (packed_shard_axes), tile blocks
    are picked against the per-shard dims, and the leaf carries its axes
    so qmatmul routes it through the shard_map'd per-shard kernel.
    Leaves whose per-shard shape loses tileability stay flat on the
    mixed dot; pass `report` to collect the (path, reason) degrades."""
    from symmetry_tpu.ops.quant import pack_tree

    axes = None
    if mesh is not None:
        if config is None:
            raise ValueError("pack_params needs `config` to resolve "
                             "per-leaf shard axes when packing on a mesh")
        axes = packed_shard_axes(config, mesh, rules)
    return pack_tree(params, QUANT_KEYS, axes=axes, mesh=mesh,
                     report=report)


def packed_shard_axes(config: ModelConfig, mesh,
                      rules: dict | None = None) -> dict:
    """leaf name -> (k_mesh_axis, n_mesh_axis) for every QUANT_KEYS leaf,
    resolved from param_logical_axes + the sharding rules — the packed
    layout shards exactly the axes the flat int8 leaf already did
    (megatron TP: wq/wk/wv/wg/wu/lm_head column-parallel over the output
    dim, wo/wd row-parallel over the contraction dim). Mesh axes of size
    1 resolve to None (nothing to shard)."""
    from symmetry_tpu.parallel.sharding import DEFAULT_RULES

    rules = DEFAULT_RULES if rules is None else rules
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out: dict = {}

    def resolve(logical):
        ax = rules.get(logical) if logical is not None else None
        return ax if ax is not None and sizes.get(ax, 1) > 1 else None

    def visit(node):
        for name, child in node.items():
            if isinstance(child, dict):
                visit(child)
            elif name in QUANT_KEYS:
                out[name] = (resolve(child[-2]), resolve(child[-1]))

    visit(param_logical_axes(config))
    return out


def packed_logical_axes(axes: dict, params: dict) -> dict:
    """Map a dense logical-axes tree to one matching a (possibly packed)
    params tree, so parallel/sharding.shardings_for composes for packed
    trees exactly as it does for flat int8 ones. A packed q keeps the
    dense dims' names on its tile-GRID dims and replicates the tile dims
    — [.., K/bk, N/bn, bk, bn] gets dense axes + (None, None) — because
    pack_quantized picks blocks against the per-shard dims, so sharding
    the grid dims IS sharding the weight. The scale maps as in
    quantized_logical_axes. Aux (mesh + axis names) is copied from the
    params leaf so the two trees stay structurally identical (the aux
    rides the treedef)."""
    from symmetry_tpu.ops.quant import PackedQuantizedTensor

    def visit(node, pnode):
        out = {}
        for name, child in node.items():
            leaf = pnode.get(name) if isinstance(pnode, dict) else None
            if isinstance(child, dict):
                out[name] = visit(child, leaf if isinstance(leaf, dict)
                                  else {})
            elif isinstance(leaf, PackedQuantizedTensor):
                out[name] = PackedQuantizedTensor(
                    q=child + (None, None),
                    scale=child[:-2] + child[-1:],
                    k_axis=leaf.k_axis, n_axis=leaf.n_axis, mesh=leaf.mesh)
            elif name in QUANT_KEYS and isinstance(
                    leaf, QuantizedTensor):
                out[name] = QuantizedTensor(
                    q=child, scale=child[:-2] + child[-1:])
            else:
                out[name] = child
        return out

    return visit(axes, params)


def quantized_logical_axes(axes: dict) -> dict:
    """Map a dense logical-axes tree to its quantized counterpart: the int8
    payload keeps the dense axes; per-column scales drop the contraction
    (second-to-last) axis."""
    def visit(node):
        out = {}
        for name, child in node.items():
            if isinstance(child, dict):
                out[name] = visit(child)
            elif name in QUANT_KEYS:
                out[name] = QuantizedTensor(
                    q=child, scale=child[:-2] + child[-1:])
            else:
                out[name] = child
        return out

    return visit(axes)


def forward(
    params: dict,
    config: ModelConfig,
    tokens: jnp.ndarray,      # [B, S] int32
    cache: KVCache,
    seq_lens: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """Run the decoder; returns (logits [B, S, vocab] f32, updated cache).

    Serves prefill (S = padded prompt length, cache.lengths typically 0) and
    decode (S = 1 per slot) with the same traced computation. Logits at
    padded positions are garbage by contract; callers index the last valid
    position.
    """
    h, cache = forward_hidden(params, config, tokens, cache, seq_lens)
    return logits_from_hidden(params, config, h), cache


# ---------------------------------------------------------------------------
# HF weight layout map (used by engine/weights.py; kept here because it is
# model knowledge). HF linear weights are [out, in] — transposed vs ours.

HF_TOP_MAP = {
    "model.embed_tokens.weight": ("embed", False),
    "model.norm.weight": ("final_norm", False),
    "lm_head.weight": ("lm_head", True),  # [V,E] -> [E,V]
}
HF_LAYER_MAP = {
    "input_layernorm.weight": ("attn_norm", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    # qwen2: QKV projection biases (absent in llama/mistral checkpoints)
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("wg", True),
    "mlp.up_proj.weight": ("wu", True),
    "mlp.down_proj.weight": ("wd", True),
}
# Mixtral: the MLP block is `block_sparse_moe` — a router (`gate`) plus
# per-expert w1/w2/w3 Linears (w1=gate_proj, w2=down_proj, w3=up_proj).
# All are HF [out, in] → transposed; experts stack on our leading dim.
HF_MOE_ROUTER = "block_sparse_moe.gate.weight"            # → router (T)
HF_EXPERT_MAP = {"w1": "wg", "w3": "wu", "w2": "wd"}      # all transposed


def hf_expert_name(layer: int, expert: int, ours: str) -> str:
    w = {v: k for k, v in HF_EXPERT_MAP.items()}[ours]
    return f"model.layers.{layer}.block_sparse_moe.experts.{expert}.{w}.weight"


def config_from_hf(hf: dict[str, Any]) -> ModelConfig:
    """Build a ModelConfig from an HF config.json dict (llama/mistral/
    qwen2/mixtral shapes; mixtral's num_local_experts selects MoEConfig)."""
    arch = (hf.get("architectures") or [""])[0]
    # Exact match: gemma-2/3 checkpoints (Gemma2ForCausalLM, ...) need
    # logit softcapping, post-layer norms, and alternating local
    # attention this decoder does not implement — loading them with
    # gemma-1 semantics would silently generate garbage.
    gemma = arch == "GemmaForCausalLM"
    if arch.startswith("Gemma") and not gemma:
        raise ValueError(
            f"unsupported architecture {arch!r}: only first-generation "
            f"GemmaForCausalLM semantics are implemented")
    # qwen2 configs carry a vestigial sliding_window alongside
    # use_sliding_window: false — honoring it would silently disable every
    # fast attention path (flash prefill, ring, the Pallas decode kernel).
    sliding = hf.get("sliding_window")
    if hf.get("use_sliding_window") is False:
        sliding = None
    if hf.get("num_local_experts"):
        return MoEConfig(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads",
                                hf["num_attention_heads"]),
            intermediate_size=hf["intermediate_size"],
            head_dim=hf.get("head_dim"),
            rope_theta=hf.get("rope_theta", 10000.0),
            rms_eps=hf.get("rms_norm_eps", 1e-5),
            tie_embeddings=hf.get("tie_word_embeddings", False),
            sliding_window=sliding,
            attention_bias=hf.get("attention_bias", "Qwen2" in arch),
            max_position=hf.get("max_position_embeddings", 8192),
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        )
    return ModelConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        intermediate_size=hf["intermediate_size"],
        head_dim=hf.get("head_dim"),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_eps=hf.get("rms_norm_eps", 1e-5),
        # gemma ties embeddings BY DEFAULT, so its config.json often omits
        # the key entirely — defaulting it False would reject the checkpoint
        tie_embeddings=hf.get("tie_word_embeddings", gemma),
        sliding_window=sliding,
        # older qwen2 configs carry no attention_bias key; the architecture
        # implies it (HF modeling_qwen2 hardcodes bias=True on q/k/v).
        attention_bias=hf.get("attention_bias", "Qwen2" in arch),
        max_position=hf.get("max_position_embeddings", 8192),
        # gemma: GeGLU + (1+w) norms + scaled embeddings; hidden_activation
        # ("gelu_pytorch_tanh") appears in newer configs, older ones imply it
        hidden_act="gelu_tanh" if gemma else "silu",
        norm_plus_one=gemma,
        scale_embed=gemma,
    )
