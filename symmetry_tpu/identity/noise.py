"""Authenticated encrypted channel: Noise-XX-pattern handshake, SIGMA-style auth.

The reference gets per-peer encryption from @hyperswarm/secret-stream (Noise XX
over libsodium; surfaced at src/types.ts:139,168-177 as `noiseStream`/`_encrypt`)
and does an *additional*, advisory-only challenge/signature verification of the
server (src/provider.ts:143-171 — logs ❌ but stays connected on failure).

This module provides the equivalent channel with the auth actually enforced:

  handshake (over length-framed plaintext frames):
    m1  I→R: eph_I                              (32B X25519 ephemeral)
    m2  R→I: eph_R ‖ Enc_k0(static_R ‖ sig_R)   sig over transcript hash
    m3  I→R: Enc_k0(static_I ‖ sig_I)

  k0 = HKDF(DH(eph, eph)) — so static identities travel encrypted (XX privacy
  property); each side signs the transcript hash with its Ed25519 identity
  (SIGMA-style explicit auth, stronger than implicit static-DH and reuses the
  node's one identity key). A handshake failure raises and the connection MUST
  be dropped by the caller — verification is not advisory.

  transport: ChaCha20-Poly1305 per direction, 64-bit counter nonces, with the
  transcript hash as the channel binding (used as AAD).

All primitives come from the `cryptography` package (OpenSSL-backed); a native
C++ cipher path for the streaming hot loop lives in native/.
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from dataclasses import dataclass

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives import serialization

from symmetry_tpu.identity.identity import Identity

_PROTO = b"symmetry-tpu/noise-xx-sigma/chacha20poly1305/blake2b:v1"


class HandshakeError(Exception):
    """Peer failed authentication or sent a malformed handshake. Drop the peer."""


def _hkdf(ikm: bytes, info: bytes, length: int) -> bytes:
    """HKDF-extract+expand over HMAC-BLAKE2b-512."""
    prk = hmac.new(b"\x00" * 64, ikm, hashlib.blake2b).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.blake2b).digest()
        out += block
        counter += 1
    return out[:length]


def _th(*parts: bytes) -> bytes:
    """Transcript hash."""
    h = hashlib.blake2b(digest_size=32)
    for p in parts:
        h.update(struct.pack(">I", len(p)))
        h.update(p)
    return h.digest()


@dataclass(repr=False)
class SecureSession:
    """Symmetric transport state after a completed handshake."""

    send_key: bytes
    recv_key: bytes
    remote_public_key: bytes  # authenticated remote Ed25519 identity
    channel_binding: bytes    # transcript hash; AAD for every transport frame

    def __repr__(self) -> str:  # never leak session keys into logs/tracebacks
        return f"SecureSession(remote={self.remote_public_key.hex()[:16]}…)"

    def __post_init__(self) -> None:
        self._send = ChaCha20Poly1305(self.send_key)
        self._recv = ChaCha20Poly1305(self.recv_key)
        self._send_n = 0
        self._recv_n = 0

    def _nonce(self, counter: int) -> bytes:
        return b"\x00\x00\x00\x00" + struct.pack("<Q", counter)

    def encrypt(self, plaintext: bytes) -> bytes:
        ct = self._send.encrypt(self._nonce(self._send_n), plaintext, self.channel_binding)
        self._send_n += 1
        return ct

    def decrypt(self, ciphertext: bytes) -> bytes:
        try:
            pt = self._recv.decrypt(self._nonce(self._recv_n), ciphertext, self.channel_binding)
        except Exception as exc:  # cryptography raises InvalidTag
            raise HandshakeError(f"transport decrypt failed: {exc}") from exc
        self._recv_n += 1
        return pt


def _session_keys(dh_ee: bytes, transcript: bytes, *, initiator: bool) -> tuple[bytes, bytes, bytes]:
    okm = _hkdf(dh_ee + transcript, _PROTO + b"/session", 64)
    k_i2r, k_r2i = okm[:32], okm[32:]
    if initiator:
        return k_i2r, k_r2i, transcript
    return k_r2i, k_i2r, transcript


def _auth_payload(identity: Identity, transcript: bytes, role: bytes) -> bytes:
    sig = identity.sign(_PROTO + role + transcript)
    return identity.public_key + sig


def _check_auth(payload: bytes, transcript: bytes, role: bytes,
                expected_remote_key: bytes | None) -> bytes:
    if len(payload) != 32 + 64:
        raise HandshakeError("malformed auth payload")
    static_pub, sig = payload[:32], payload[32:]
    if expected_remote_key is not None and static_pub != expected_remote_key:
        raise HandshakeError("remote static key does not match expected key")
    if not Identity.verify(_PROTO + role + transcript, sig, static_pub):
        raise HandshakeError("bad handshake signature")
    return static_pub


async def client_handshake(conn, identity: Identity,
                           expected_remote_key: bytes | None = None) -> SecureSession:
    """Initiator side. `conn` must expose async send(bytes)/recv()->bytes frames."""
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    await conn.send(eph_pub)

    m2 = await conn.recv()
    if m2 is None or len(m2) < 32:
        raise HandshakeError("handshake aborted")
    remote_eph_pub, ct = m2[:32], m2[32:]
    dh_ee = eph.exchange(X25519PublicKey.from_public_bytes(remote_eph_pub))
    k0 = ChaCha20Poly1305(_hkdf(dh_ee, _PROTO + b"/hs", 32))

    t1 = _th(_PROTO, eph_pub, remote_eph_pub)
    try:
        payload = k0.decrypt(b"\x00" * 11 + b"\x00", ct, t1)
    except Exception as exc:
        raise HandshakeError(f"m2 decrypt failed: {exc}") from exc
    remote_static = _check_auth(payload, t1, b"resp", expected_remote_key)

    t2 = _th(t1, payload)
    my_auth = _auth_payload(identity, t2, b"init")
    await conn.send(k0.encrypt(b"\x00" * 11 + b"\x01", my_auth, t2))

    transcript = _th(t2, my_auth)
    send_key, recv_key, binding = _session_keys(dh_ee, transcript, initiator=True)
    return SecureSession(send_key, recv_key, remote_static, binding)


async def server_handshake(conn, identity: Identity,
                           expected_remote_key: bytes | None = None) -> SecureSession:
    """Responder side."""
    m1 = await conn.recv()
    if m1 is None or len(m1) != 32:
        raise HandshakeError("bad m1")
    remote_eph_pub = m1
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    dh_ee = eph.exchange(X25519PublicKey.from_public_bytes(remote_eph_pub))
    k0 = ChaCha20Poly1305(_hkdf(dh_ee, _PROTO + b"/hs", 32))

    t1 = _th(_PROTO, remote_eph_pub, eph_pub)
    my_auth = _auth_payload(identity, t1, b"resp")
    await conn.send(eph_pub + k0.encrypt(b"\x00" * 11 + b"\x00", my_auth, t1))

    t2 = _th(t1, my_auth)
    m3 = await conn.recv()
    if m3 is None:
        raise HandshakeError("handshake aborted")
    try:
        payload = k0.decrypt(b"\x00" * 11 + b"\x01", m3, t2)
    except Exception as exc:
        raise HandshakeError(f"m3 decrypt failed: {exc}") from exc
    remote_static = _check_auth(payload, t2, b"init", expected_remote_key)

    transcript = _th(t2, payload)
    send_key, recv_key, binding = _session_keys(dh_ee, transcript, initiator=False)
    return SecureSession(send_key, recv_key, remote_static, binding)
