"""Serving benchmark: aggregate decode throughput of the tpu_native engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline is measured against the BASELINE.json north-star target of
2000 tok/s aggregate (llama3:8b streaming on v5e-8 — reference publishes no
numbers of its own, SURVEY §6, so the target is the yardstick).

Modes:
  python bench.py            # real chip: llama3.2-1b-shaped model, bf16
  python bench.py --smoke    # CPU-safe tiny model (used by /verify)
  python bench.py --preset llama3-8b --slots 16 --steps 256 ...
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run_bench(preset_name: str, *, slots: int, steps: int, prompt_len: int,
              max_seq: int, dtype_name: str, mesh_model: int,
              block: int = 1, quant: str | None = None,
              kv_quant: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, param_logical_axes, preset
    from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype_name]
    config = preset(preset_name)

    if mesh_model > 1:
        mesh = build_mesh(MeshSpec(data=1, model=mesh_model))
        params = jax.device_put(
            init_params(config, jax.random.key(0), dtype),
            shardings_for(param_logical_axes(config), mesh))
        # Quantize AFTER placement: the dense sharding tree doesn't
        # prefix-match QuantizedTensor leaves; jitted quantize preserves
        # input shardings.
        if quant == "int8":
            from symmetry_tpu.models.llama import quantize_params

            params = quantize_params(params)
    else:
        mesh = None
        # Single chip: init leaves directly in int8 so models whose bf16
        # form exceeds HBM (llama3-8b on v5e) still fit.
        params = init_params(config, jax.random.key(0), dtype,
                             quantize=quant == "int8")

    engine = InferenceEngine(
        config, params, ByteTokenizer(), mesh=mesh, max_slots=slots,
        max_seq_len=max_seq, prefill_buckets=(prompt_len,),
        cache_dtype=dtype, decode_block=block, kv_quant=kv_quant)

    # Compile the decode program BEFORE inserting real requests (warmup's
    # garbage device writes are only harmless pre-insert).
    engine.warmup()

    prompt = list(range(1, prompt_len + 1))
    t_prefill0 = time.perf_counter()
    group = max(engine.PREFILL_BATCHES)
    for start in range(0, slots, group):
        engine.prefill_and_insert_many(
            [(slot, [p % 200 for p in prompt],
              SamplingParams(temperature=0.7, seed=slot))
             for slot in range(start, min(start + group, slots))])
    prefill_s = time.perf_counter() - t_prefill0

    import numpy as np

    # One warm dispatch, then measure. `steps` counts decode steps; each
    # dispatch advances `block` of them. Double-buffered like the serving
    # scheduler: block N+1 is dispatched before syncing block N's tokens,
    # so the host round-trip rides behind device compute.
    engine.decode_steps()
    n_disp = max(1, steps // block)
    t0 = time.perf_counter()
    pending = None
    for _ in range(n_disp):
        nxt = engine.decode_steps_dispatch()
        if pending is not None:
            np.asarray(pending)
        pending = nxt
    np.asarray(pending)
    dt = time.perf_counter() - t0

    done_steps = n_disp * block
    tok_s = slots * done_steps / dt
    dtype_label = f"{dtype_name}+{quant}" if quant else dtype_name
    if kv_quant:
        dtype_label += "+kv8"
    dtype_name = dtype_label
    return {
        "metric": f"aggregate decode tok/s ({preset_name} {dtype_name}, "
                  f"{slots} slots, block {block}, "
                  f"{jax.device_count()} {jax.default_backend()} dev)",
        "value": round(tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / 2000.0, 3),
        "per_slot_tok_s": round(tok_s / slots, 1),
        "prefill_s_per_slot": round(prefill_s / slots, 3),
        "decode_step_ms": round(1e3 * dt / done_steps, 2),
    }


def run_e2e(preset_name: str, *, clients: int, slots: int, max_new: int,
            prompt_chars: int, max_seq: int, dtype_name: str, block: int,
            quant: str | None, kv_quant: bool, bucket: int) -> dict:
    """The NORTH-STAR measurement (BASELINE.json metric): aggregate WIRE
    tok/s and p50/p99 TTFT through the full serving path — server +
    tpu_native provider + N concurrent streaming clients over TCP
    loopback. This is the serving-path analog of the reference's hot loop
    (reference: src/provider.ts:240-258), where the engine-only bench
    (run_bench) measures just the decode kernel underneath it."""
    import asyncio
    import statistics
    import time as _time

    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.tcp import TcpTransport

    model_name = f"{preset_name}:bench"
    cfg = ConfigManager(config={
        "name": "bench-prov",
        "public": True,
        "serverKey": Identity.from_name("bench-server").public_hex,
        "modelName": model_name,
        "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "maxConnections": clients + 8,
        "tpu": {
            "model_preset": preset_name,
            "dtype": dtype_name,
            "quantization": quant,
            "kv_quantization": "int8" if kv_quant else None,
            "max_batch_size": slots,
            "max_seq_len": max_seq,
            "prefill_buckets": [bucket],
            "decode_block": block,
        },
    })

    async def main() -> dict:
        server_ident = Identity.from_name("bench-server")
        server = SymmetryServer(server_ident, TcpTransport(),
                                ping_interval_s=60.0)
        await server.start("tcp://127.0.0.1:0")
        provider = SymmetryProvider(
            cfg, transport=TcpTransport(),
            identity=Identity.from_name("bench-prov"),
            server_address=server.address)
        # start() builds + warms the engine (minutes for 8B: weight init,
        # XLA compiles); none of that counts toward the measured window.
        await provider.start("tcp://127.0.0.1:0")
        await provider.wait_registered(timeout=1800)

        prompt = "x" * prompt_chars

        async def one_client(i: int) -> dict:
            client = SymmetryClient(Identity.from_name(f"bench-cli-{i}"),
                                    TcpTransport())
            details = await client.request_provider(
                server.address, server_ident.public_key, model_name)
            session = await client.connect(details)
            t_send = _time.perf_counter()
            t_first = None
            chars = 0
            try:
                async for delta in session.chat(
                        [{"role": "user", "content": prompt}],
                        max_tokens=max_new, temperature=0.7, seed=i):
                    if t_first is None and delta:
                        t_first = _time.perf_counter()
                    chars += len(delta)
            finally:
                await session.close()
            t_done = _time.perf_counter()
            return {"ttft": (t_first or t_done) - t_send,
                    "e2e": t_done - t_send, "chars": chars}

        t0 = _time.perf_counter()
        results = await asyncio.gather(
            *(one_client(i) for i in range(clients)))
        elapsed = _time.perf_counter() - t0

        # True sampled-token count from the scheduler (ByteTokenizer chars
        # under-count: multi-byte UTF-8 assemblies collapse several byte
        # tokens into one char on the wire).
        sched = provider.backend._scheduler
        tokens = sched.metrics["tokens"]
        peak = sched.metrics["peak_occupancy"]

        await provider.stop(drain_timeout_s=5)
        await server.stop()

        ttfts = sorted(r["ttft"] for r in results)
        e2es = sorted(r["e2e"] for r in results)

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        tok_s = tokens / elapsed
        dtype_label = f"{dtype_name}+{quant}" if quant else dtype_name
        if kv_quant:
            dtype_label += "+kv8"
        import jax

        return {
            "metric": f"e2e serving tok/s ({preset_name} {dtype_label}, "
                      f"{clients} streaming clients over TCP, {slots} slots, "
                      f"block {block}, "
                      f"{jax.device_count()} {jax.default_backend()} dev)",
            "value": round(tok_s, 1),
            "unit": "tok/s",
            "vs_baseline": round(tok_s / 2000.0, 3),
            "ttft_p50_s": round(pct(ttfts, 0.50), 3),
            "ttft_p99_s": round(pct(ttfts, 0.99), 3),
            "e2e_p50_s": round(pct(e2es, 0.50), 3),
            "e2e_p99_s": round(pct(e2es, 0.99), 3),
            "tokens_streamed": tokens,
            "wall_s": round(elapsed, 2),
            "peak_occupancy": peak,
            "mean_ttft_s": round(statistics.mean(ttfts), 3),
        }

    return asyncio.new_event_loop().run_until_complete(main())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-safe tiny-model run (verification, not perf)")
    ap.add_argument("--e2e", action="store_true",
                    help="full serving path: server + provider + N "
                         "streaming clients over TCP (north-star metric)")
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=128)
    ap.add_argument("--steps", type=int, default=192)
    ap.add_argument("--clients", type=int, default=128,
                    help="concurrent streaming clients (--e2e)")
    ap.add_argument("--max-new", type=int, default=256,
                    help="tokens per client request (--e2e)")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--max-seq", type=int, default=640)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=("bfloat16", "float32"))
    ap.add_argument("--mesh-model", type=int, default=1,
                    help="model-axis mesh size (tensor parallelism)")
    ap.add_argument("--block", type=int, default=64,
                    help="decode steps per device dispatch")
    ap.add_argument("--quant", default="int8", choices=("none", "int8"),
                    help="weight quantization")
    ap.add_argument("--kv-quant", default="int8", choices=("none", "int8"),
                    help="KV cache quantization")
    args = ap.parse_args()

    if args.smoke:
        # Smoke mode must not touch a TPU: pin the CPU backend before any
        # jax usage (env alone can be overridden by site hooks).
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_bench("tiny", slots=2, steps=8, prompt_len=16,
                           max_seq=64, dtype_name="float32", mesh_model=1,
                           block=2)
    elif args.e2e:
        result = run_e2e(
            args.preset, clients=args.clients, slots=args.slots,
            # ~24 tokens of headroom for the chat template + BOS so the
            # rendered prompt still fits the --prompt-len bucket
            max_new=args.max_new, prompt_chars=max(1, args.prompt_len - 24),
            max_seq=args.max_seq, dtype_name=args.dtype, block=args.block,
            quant=None if args.quant == "none" else args.quant,
            kv_quant=args.kv_quant == "int8", bucket=args.prompt_len)
    else:
        result = run_bench(args.preset, slots=args.slots, steps=args.steps,
                           prompt_len=args.prompt_len, max_seq=args.max_seq,
                           dtype_name=args.dtype, mesh_model=args.mesh_model,
                           block=args.block,
                           quant=None if args.quant == "none" else args.quant,
                           kv_quant=args.kv_quant == "int8")
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
