"""Serving benchmark: aggregate decode throughput of the tpu_native engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline is measured against the BASELINE.json north-star target of
2000 tok/s aggregate (llama3:8b streaming on v5e-8 — reference publishes no
numbers of its own, SURVEY §6, so the target is the yardstick).

Modes:
  python bench.py            # NORTH STAR: full serving path (server +
                             # tpu_native provider subprocess + 128
                             # streaming TCP clients), llama3-8b int8;
                             # falls back to --engine on failure
  python bench.py --engine   # engine-only decode loop (no wire)
  python bench.py --smoke    # CPU-safe tiny model (used by /verify)
  python bench.py --e2e --clients 64 --max-new 128 ...
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _rnd(x, nd: int = 3):
    return round(x, nd) if isinstance(x, (int, float)) else x


# Bench-JSON schema version: bumped when the capture's SHAPE changes in
# a way tools/benchdiff.py must know about (v1 = the stamped format —
# schema + git_sha + resolved-knob config fingerprint on every capture).
BENCH_SCHEMA = 1


def _git_sha() -> str | None:
    """The repo HEAD this capture ran at (None outside a git checkout) —
    benchdiff prints both SHAs so a delta names its endpoints."""
    import os
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def stamp_result(result: dict, config: dict, mode: str) -> dict:
    """Stamp a bench capture with its identity: schema version, git SHA,
    and the RESOLVED-knob config fingerprint (every knob that shapes the
    measurement, post-default-resolution — not the raw argv). benchdiff
    refuses to compare captures whose fingerprints disagree: a tok/s
    delta between a 128-slot run and a 96-slot run is a config diff
    wearing a regression costume, and the old eyeballed-JSON workflow
    produced exactly that garbage silently."""
    import hashlib

    cfg = {"mode": mode, **{k: config[k] for k in sorted(config)}}
    digest = hashlib.blake2b(
        json.dumps(cfg, sort_keys=True, separators=(",", ":")).encode(),
        digest_size=8).hexdigest()
    result["schema"] = BENCH_SCHEMA
    result["git_sha"] = _git_sha()
    result["written_at"] = round(time.time(), 1)
    result["config"] = cfg
    result["config_fingerprint"] = digest
    return result


def arrival_times(kind: str, n: int, *, duration_s: float,
                  seed: int = 0) -> list[float]:
    """Deterministic arrival-offset traces for the open-loop workloads
    (`--arrival`): n send offsets in [0, duration_s), sorted. Seeded so
    every arm of a comparison bench (run_autoscale) replays the SAME
    trace — the topology is the only variable.

    - poisson: homogeneous Poisson arrivals (exponential inter-arrival
      gaps at rate n/duration), rescaled to span the window exactly.
    - diurnal: inhomogeneous Poisson with a sinusoidal intensity —
      trough at both ends, one peak mid-trace at ~19x the trough rate
      (lam(t) = 1 - 0.9*cos(2*pi*t/D)); sampled by inverting the
      closed-form cumulative intensity. The day-curve in miniature:
      the shape where a static topology must provision for the peak.
    - burst: 4 near-simultaneous waves evenly spaced through the
      window — the thundering-herd shape the autoscale smoke uses.
    """
    import math
    import random

    if n <= 0:
        return []
    rnd = random.Random(seed)
    if kind == "poisson":
        rate = n / max(duration_s, 1e-9)
        t, out = 0.0, []
        for _ in range(n):
            t += rnd.expovariate(rate)
            out.append(t)
        scale = duration_s / max(out[-1], 1e-9)
        return [x * scale for x in out]
    if kind == "diurnal":
        amp = 0.9

        def cum(t: float) -> float:  # normalized cumulative intensity
            return (t - amp * duration_s / (2 * math.pi)
                    * math.sin(2 * math.pi * t / duration_s)) / duration_s

        out = []
        for i in range(n):
            # Stratified uniforms keep the realized trace close to the
            # intensity curve even at small n.
            u = (i + rnd.random()) / n
            lo, hi = 0.0, duration_s
            for _ in range(48):
                mid = (lo + hi) / 2
                if cum(mid) < u:
                    lo = mid
                else:
                    hi = mid
            out.append((lo + hi) / 2)
        return sorted(out)
    if kind == "burst":
        waves = 4
        per = -(-n // waves)
        jitter = 0.02 * duration_s / waves
        return sorted((i // per + 0.5) * duration_s / waves
                      + rnd.random() * jitter for i in range(n))
    raise ValueError(f"unknown arrival kind {kind!r} "
                     f"(want poisson|diurnal|burst)")


import contextlib


@contextlib.asynccontextmanager
async def _provider_process(cfg: dict, server, model_name: str, *,
                            timeout_s: float, stdout):
    """Spawn `python -m symmetry_tpu.provider` on a temp config and wait
    for it to register with `server`; yields (proc, startup_s). One
    definition of the launch/registration/teardown lifecycle for every
    bench mode — the registration wait and the teardown live in the same
    try/finally, so a never-registering provider cannot leak the
    subprocess or the temp config (it holds privateSeed)."""
    import asyncio
    import os
    import subprocess
    import sys
    import tempfile
    import time as _time

    import yaml

    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as fh:
        yaml.safe_dump(cfg, fh)
        cfg_path = fh.name
    proc = subprocess.Popen(
        [sys.executable, "-m", "symmetry_tpu.provider", "-c", cfg_path],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=stdout, stderr=subprocess.STDOUT)
    try:
        t_start = _time.monotonic()
        deadline = t_start + timeout_s
        while server.registry.select_provider(model_name) is None:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"provider process exited rc={proc.returncode}")
            if _time.monotonic() > deadline:
                raise TimeoutError("provider never registered")
            await asyncio.sleep(0.5)
        yield proc, _time.monotonic() - t_start
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        os.unlink(cfg_path)


def run_bench(preset_name: str, *, slots: int, steps: int, prompt_len: int,
              max_seq: int, dtype_name: str, mesh_model: int,
              block: int = 1, quant: str | None = None,
              kv_quant: bool = False, fused_dequant: bool = False,
              profile_sample: int = 0, pipeline_depth: int = 1) -> dict:
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, param_logical_axes, preset
    from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype_name]
    config = preset(preset_name)

    if mesh_model > 1:
        mesh = build_mesh(MeshSpec(data=1, model=mesh_model))
        params = jax.device_put(
            init_params(config, jax.random.key(0), dtype),
            shardings_for(param_logical_axes(config), mesh))
        # Quantize AFTER placement: the dense sharding tree doesn't
        # prefix-match QuantizedTensor leaves; jitted quantize preserves
        # input shardings.
        if quant == "int8":
            from symmetry_tpu.models.llama import quantize_params

            params = quantize_params(params)
    else:
        mesh = None
        # Single chip: init leaves directly in int8 so models whose bf16
        # form exceeds HBM (llama3-8b on v5e) still fit.
        params = init_params(config, jax.random.key(0), dtype,
                             quantize=quant == "int8")

    engine = InferenceEngine(
        config, params, ByteTokenizer(), mesh=mesh, max_slots=slots,
        max_seq_len=max_seq, prefill_buckets=(prompt_len,),
        cache_dtype=dtype, decode_block=block, kv_quant=kv_quant,
        fused_dequant=fused_dequant, profile_sample=profile_sample)

    # Compile the decode program BEFORE inserting real requests (warmup's
    # garbage device writes are only harmless pre-insert).
    engine.warmup()

    prompt = list(range(1, prompt_len + 1))
    t_prefill0 = time.perf_counter()
    group = max(engine.PREFILL_BATCHES)
    for start in range(0, slots, group):
        engine.prefill_and_insert_many(
            [(slot, [p % 200 for p in prompt],
              SamplingParams(temperature=0.7, seed=slot))
             for slot in range(start, min(start + group, slots))])
    prefill_s = time.perf_counter() - t_prefill0

    import numpy as np

    # One warm dispatch, then measure. `steps` counts decode steps; each
    # dispatch advances `block` of them. Pipelined like the serving
    # scheduler (--pipeline-depth, default 1 = the historical double
    # buffer: block N+1 dispatched before syncing block N's tokens):
    # `depth` blocks stay in flight, the oldest is synced once the
    # pipeline is full. Per-iteration host wall is sampled so the bench
    # JSON carries the dispatch-thread-per-block number the scheduler's
    # stats() splits out (here there is no emit work, so this is the
    # floor: dispatch + sync cost alone).
    from collections import deque

    engine.decode_steps()
    n_disp = max(1, steps // block)
    depth = max(1, pipeline_depth)
    in_flight: deque = deque()
    iter_walls: list[float] = []
    t0 = time.perf_counter()
    for _ in range(n_disp):
        t_it = time.perf_counter()
        in_flight.append(engine.decode_steps_dispatch())
        if len(in_flight) > depth:
            np.asarray(in_flight.popleft())
        iter_walls.append(time.perf_counter() - t_it)
    while in_flight:
        np.asarray(in_flight.popleft())
    dt = time.perf_counter() - t0
    walls = sorted(iter_walls)
    disp_wall = {
        "p50": round(walls[len(walls) // 2], 6),
        "p99": round(walls[min(len(walls) - 1,
                               int(len(walls) * 0.99))], 6),
    }

    done_steps = n_disp * block
    tok_s = slots * done_steps / dt
    # symprof block (tpu.profile_sample): per-dispatch-kind DEVICE
    # duration p50s + the dispatch-gap share — the engine-only bench
    # exercises prefill + decode_block; the serving bench covers the
    # full kind set through the scheduler.
    devprof_block = None
    if profile_sample:
        dstats = engine.devprof.stats()
        devprof_block = dict(dstats)
        devprof_block["device_p50_ms"] = {
            kind: _rnd(1e3 * h["p50"], 3) if h.get("p50") else None
            for kind, h in (dstats.get("device_s") or {}).items()}
    dtype_label = f"{dtype_name}+{quant}" if quant else dtype_name
    if kv_quant:
        dtype_label += "+kv8"
    if fused_dequant:
        dtype_label += "+fused"
    dtype_name = dtype_label
    # Convert-wall accounting: the weight bytes every decode step streams
    # and the effective HBM rate they moved at — the number the fused-
    # dequant A/B exists to raise (BASELINE.md decode-floor section).
    step_s = dt / done_steps
    weight_bytes = engine.weight_stream_bytes()
    # Per-device stream: with the packed layout sharded over the mesh
    # each chip reads only its weight shard per step — THIS is the
    # number a per-chip HBM roofline bounds, and the TP A/B gate
    # (BASELINE.md round-19) compares. Equals the aggregate figure
    # on a single device.
    weight_bytes_dev = engine.weight_stream_bytes_per_device()
    return {
        "metric": f"aggregate decode tok/s ({preset_name} {dtype_name}, "
                  f"{slots} slots, block {block}, "
                  f"{jax.device_count()} {jax.default_backend()} dev)",
        "value": round(tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / 2000.0, 3),
        "per_slot_tok_s": round(tok_s / slots, 1),
        "prefill_s_per_slot": round(prefill_s / slots, 3),
        "decode_step_ms": round(1e3 * step_s, 2),
        "weight_bytes_per_step": weight_bytes,
        "weight_stream_gbs": round(weight_bytes / step_s / 1e9, 1),
        "weight_stream_gbs_per_device": round(
            weight_bytes_dev / step_s / 1e9, 1),
        "pipeline_depth": depth,
        "dispatch_thread_block_s": disp_wall,
        **({"devprof": devprof_block} if devprof_block else {}),
    }


def run_e2e_client_worker() -> int:
    """One shard of the e2e bench's client fleet, in its OWN process.

    Round 4 measured the 128-client wire tail through a saturated
    instrument: 128 concurrent Noise-decrypting asyncio streams in ONE
    event loop meant the reported inter-chunk gap p99 (1.25-2.0 s) partly
    measured the bench client itself — the engine-side histogram said
    p99 ≤ 0.63 s. Sharding the fleet over N OS processes removes the
    client loop from the measurement.

    Protocol (parent = run_e2e): read one JSON config line on stdin →
    connect every assigned session → print "READY <n>" → block for the
    "GO" line (the cross-process burst barrier) → run the clients →
    print "RESULTS <json>". All timestamps are time.monotonic(), which is
    CLOCK_MONOTONIC — one clock across processes on Linux, so the parent
    can aggregate absolute stamps from every shard."""
    import asyncio
    import time as _time

    from symmetry_tpu.client.client import ProviderBusyError, SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.transport.tcp import TcpTransport

    spec = json.loads(sys.stdin.readline())
    server_address = spec["server_address"]
    server_key = bytes.fromhex(spec["server_key_hex"])
    model_name = spec["model_name"]
    indices: list[int] = spec["indices"]
    # Per-session prompts (aligned with `indices`): the shared-prefix
    # workload gives every client its own prompt; uniform workloads send
    # the same string for all. Legacy "prompt" still accepted.
    prompts: list[str] = (spec.get("prompts")
                          or [spec["prompt"]] * len(indices))
    max_new: int = spec["max_new"]
    stagger_s: float = spec["stagger_s"]
    # Open-loop arrival trace (--arrival): per-session send offsets
    # aligned with `indices`, overriding the linear stagger.
    arrivals: list[float] | None = spec.get("arrivals")
    # Wave-level request controls: the speculative bench runs a greedy
    # (temperature 0) workload, wave A opting every request out of
    # drafting ("speculative": false) so the same provider measures the
    # plain path and the speculative path on identical prompts.
    temperature: float = spec.get("temperature", 0.7)
    spec_flag: bool | None = spec.get("speculative")

    async def main() -> list[dict]:
        ready = asyncio.Event()

        async def one_client(i: int, prompt: str, delay_s: float) -> dict:
            client = SymmetryClient(Identity.from_name(f"bench-cli-{i}"),
                                    TcpTransport())
            details = await client.request_provider(
                server_address, server_key, model_name)
            session = await client.connect(details)
            sessions_up[0] += 1
            if sessions_up[0] == len(indices):
                all_connected.set()
            await ready.wait()
            # Global arrival order by GLOBAL index — the shards together
            # reproduce exactly the single-process arrival pattern.
            await asyncio.sleep(delay_s)
            t_send = _time.monotonic()
            t_first = None
            chars = 0
            stamps: list[tuple[float, int]] = []
            try:
                async for delta in session.chat(
                        [{"role": "user", "content": prompt}],
                        max_tokens=max_new, temperature=temperature,
                        seed=i, speculative=spec_flag):
                    now = _time.monotonic()
                    if t_first is None and delta:
                        t_first = now
                    chars += len(delta)
                    stamps.append((now, len(delta)))
                tokens = int((session.last_usage or {}).get("tokens", 0))
            except ProviderBusyError as exc:
                return {"rejected": True,
                        "reject_s": _time.monotonic() - t_send,
                        "queue_depth": exc.queue_depth}
            finally:
                await session.close()
            t_done = _time.monotonic()
            # symledger cost block from the end frame (tpu.ledger on):
            # the request's attributed device time rides the capture so
            # the parent can report cost percentiles + wasted share.
            costs = getattr(session, "last_costs", None)
            return {"ttft": (t_first or t_done) - t_send,
                    "e2e": t_done - t_send, "chars": chars,
                    "tokens": tokens, "t_first": t_first or t_done,
                    "t_done": t_done, "stamps": stamps,
                    **({"costs": costs} if costs else {})}

        sessions_up = [0]
        all_connected = asyncio.Event()
        tasks = [asyncio.ensure_future(one_client(
                     i, prompts[k],
                     arrivals[k] if arrivals is not None
                     else i * stagger_s))
                 for k, i in enumerate(indices)]
        await asyncio.wait_for(all_connected.wait(), timeout=120)
        print(f"READY {len(indices)}", flush=True)
        loop = asyncio.get_running_loop()
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line.startswith("GO"):
            raise RuntimeError(f"expected GO, got {line!r}")
        ready.set()
        return list(await asyncio.gather(*tasks))

    results = asyncio.new_event_loop().run_until_complete(main())
    print("RESULTS " + json.dumps(results), flush=True)
    return 0


def run_chaos(preset_name: str, *, clients: int, slots: int, max_new: int,
              prompt_chars: int, max_seq: int, dtype_name: str, block: int,
              bucket: int, seam: str) -> dict:
    """The kill-under-load robustness bench (`--chaos`): arm ONE named
    fault seam on provider 1's engine host (default: a pipe-write crash
    that lands mid-stream), drive a concurrent client fleet through
    chat_failover, and run the SAME drill twice — stream resumption on
    (the default failure model) vs off (legacy discard-and-restart).
    The headline is WASTED WORK: tokens generated and then thrown away
    (restart arm: every discarded partial; resume arm: only offset-dedup
    drops and refused-resume fallbacks) plus the recovery latency from
    the failure sentinel to the next delivered delta (post-kill TTFT).

    Providers live in this process over the in-memory transport (the
    engine hosts are still real subprocesses) — this bench measures
    recovery behavior and wasted work, not peak wire throughput; the
    north-star numbers stay with --e2e."""
    import asyncio
    import statistics
    import time as _time

    from symmetry_tpu.client.client import (
        ChatRestart,
        ChatResume,
        ClientError,
        SymmetryClient,
    )
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.memory import MemoryTransport
    from symmetry_tpu.utils.faults import FAULTS

    seam_name, sep, seam_spec = seam.partition("=")
    if not sep or not seam_name or not seam_spec:
        raise RuntimeError(f"--chaos-seam wants seam=action@trigger, "
                           f"got {seam!r}")

    def pct(vals, p):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              max(0, -(-p * len(vals) // 100) - 1))], 4)

    async def run_arm(resume_on: bool) -> dict:
        FAULTS.clear()
        hub = MemoryTransport()
        ident = Identity.from_name("chaos-bench-server")
        server = SymmetryServer(ident, hub, ping_interval_s=60.0)
        await server.start("mem://chaos-server")

        def provider_cfg(name: str, faults: dict | None) -> ConfigManager:
            return ConfigManager(config={
                "name": name, "public": True,
                "serverKey": ident.public_hex,
                "modelName": f"{preset_name}:chaos",
                "apiProvider": "tpu_native",
                "dataCollectionEnabled": False,
                "maxConnections": clients + 8,
                "flightRecorder": {"enabled": False},
                **({"faults": faults} if faults else {}),
                "tpu": {"model_preset": preset_name, "dtype": dtype_name,
                        "max_batch_size": slots, "max_seq_len": max_seq,
                        "prefill_buckets": [bucket],
                        "decode_block": block,
                        # The resume admission path seeds through the
                        # radix cache — on, so resumes are cheap
                        # re-prefills, the contract under test.
                        "prefix_cache_mb": 64.0},
            })

        providers = []
        for name, faults in (("chaos-p1", {seam_name: seam_spec}),
                             ("chaos-p2", None)):
            prov = SymmetryProvider(
                provider_cfg(name, faults), transport=hub,
                identity=Identity.from_name(name),
                server_address="mem://chaos-server")
            await prov.start(f"mem://{name}")
            await prov.wait_registered()
            providers.append(prov)
        p1, p2 = providers
        # Steer the first wave at the faulted provider.
        server.registry.set_connections(p2.identity.public_hex, 5)

        prompts = [(f"req {i:04d} " + "resume the work under fire "
                    * 64)[:prompt_chars] for i in range(clients)]
        per_req: list[dict] = []

        async def one(i: int) -> None:
            client = SymmetryClient(
                Identity.from_name(f"chaos-cli-{i}"), hub)
            row = {"completed": False, "resumes": 0, "restarts": 0,
                   "resumed_tokens": 0, "discarded_tokens": 0,
                   "recovery_s": []}
            t_fail = None
            try:
                async for item in client.chat_failover(
                        "mem://chaos-server", ident.public_key,
                        f"{preset_name}:chaos",
                        [{"role": "user", "content": prompts[i]}],
                        max_tokens=max_new, resume=resume_on,
                        attempts=4, busy_retry_rounds=2):
                    if isinstance(item, ChatResume):
                        row["resumes"] += 1
                        row["resumed_tokens"] += item.resumed_tokens or 0
                        t_fail = _time.monotonic()
                    elif isinstance(item, ChatRestart):
                        row["restarts"] += 1
                        row["discarded_tokens"] += (
                            item.discarded_tokens or 0)
                        t_fail = _time.monotonic()
                    elif item and t_fail is not None:
                        row["recovery_s"].append(
                            _time.monotonic() - t_fail)
                        t_fail = None
                row["completed"] = True
            except ClientError as exc:
                row["error"] = str(exc)
            per_req.append(row)

        t0 = _time.monotonic()
        await asyncio.gather(*[one(i) for i in range(clients)])
        wall = _time.monotonic() - t0
        tokens_streamed = sum(p.metrics["tokens_out"] for p in providers)
        dedup = sum(p.backend.resume_stats["dedup_dropped"]
                    for p in providers
                    if hasattr(p.backend, "resume_stats"))
        for prov in providers:
            await prov.stop(drain_timeout_s=2)
        await server.stop()
        FAULTS.clear()
        recoveries = [r for row in per_req for r in row["recovery_s"]]
        discarded = sum(r["discarded_tokens"] for r in per_req)
        return {
            "resumption": resume_on,
            "requests": clients,
            "completed": sum(r["completed"] for r in per_req),
            "failed": sum(not r["completed"] for r in per_req),
            "wall_s": round(wall, 2),
            "tokens_streamed": tokens_streamed,
            "resumes": sum(r["resumes"] for r in per_req),
            "restarts": sum(r["restarts"] for r in per_req),
            "resumed_tokens": sum(r["resumed_tokens"] for r in per_req),
            # Wasted work = tokens generated then thrown away: discarded
            # partials (restart path) + overlap the relay dedup dropped
            # (resume path) — regenerated − resumed, per the Round-14
            # protocol.
            "wasted_tokens": discarded + dedup,
            "discarded_tokens": discarded,
            "dedup_dropped_tokens": dedup,
            "recovery_s": {"n": len(recoveries),
                           "p50": pct(recoveries, 50),
                           "p99": pct(recoveries, 99),
                           "mean": (round(statistics.mean(recoveries), 4)
                                    if recoveries else None)},
        }

    async def main() -> dict:
        arms = {}
        for resume_on in (True, False):
            label = "resume" if resume_on else "restart"
            print(f"[chaos] arm {label}: {clients} clients, seam {seam}",
                  file=sys.stderr)
            arms[label] = await run_arm(resume_on)
            print(f"[chaos] arm {label}: "
                  f"{arms[label]['completed']}/{clients} completed, "
                  f"wasted {arms[label]['wasted_tokens']} tok, "
                  f"resumed {arms[label]['resumed_tokens']} tok",
                  file=sys.stderr)
        saved = (arms["restart"]["wasted_tokens"]
                 - arms["resume"]["wasted_tokens"])
        return {
            "kind": "chaos",
            "preset": preset_name,
            "clients": clients, "slots": slots, "max_new": max_new,
            "seam": seam,
            "arms": arms,
            # The robustness headline: wasted-work tokens the resume
            # path saved vs shed-and-retry, at identical kill schedules.
            "wasted_tokens_saved": saved,
        }

    return asyncio.new_event_loop().run_until_complete(main())


def run_autoscale(preset_name: str, *, clients: int, slots: int,
                  max_new: int, prompt_chars: int, max_seq: int,
                  dtype_name: str, block: int, bucket: int,
                  arrival: str, duration_s: float, seed: int,
                  slo_ttft_s: float, slo_chunk_s: float,
                  objective: float, static_shapes: tuple[str, ...],
                  max_members: int) -> dict:
    """The SLO-goodput autoscaling bench (`--autoscale`): replay ONE
    seeded arrival trace (default: the diurnal curve — trough, peak,
    trough) against an autoscaled pool and against each static MxN
    control, all in one invocation. The autoscaled arm starts at the
    FIRST static shape — the hand-picked constant under test — and the
    controller right-sizes it against the trace (floor 1x1, ceiling
    tpu.autoscale.max_members). Every arm reports SLO attainment
    (client-side TTFT + inter-chunk gap vs the targets), CHIP-SECONDS
    (sum of pool-member alive time over the TRACE window — boot warmup
    is excluded so arms compare provisioning, not compile-cache state;
    members spawned mid-trace pay their whole life, warmup included),
    and the headline GOODPUT: SLO-attaining tokens per chip-second.

    The autoscaled arm runs the real closed loop: a SloMonitor observes
    the same traffic (the bench performs the provider's exact observe
    calls — TTFT on first delta, inter-chunk gaps as they arrive), the
    pool heartbeat feeds burn rates + queue gauges + symprof busy-time
    into PoolAutoscaler (engine/disagg/autoscale.py), and its decisions
    spawn/drain real members mid-trace. The verdict the capture
    records: does the autoscaled arm meet the SLOs with fewer
    chip-seconds than every static shape that also meets them?

    Backend-direct like disagg_smoke's fallback mode: the fleet drives
    TpuNativeBackend in this process (engine hosts are still real
    subprocesses) with no server/client wire between — this measures
    topology economics, not wire throughput, and stays runnable where
    the `cryptography` network dependency is absent. Tokens are counted
    as streamed chars (exact under the byte tokenizer every preset here
    serves)."""
    import asyncio
    import os as _os
    import time as _time
    import uuid as _uuid

    # Engine hosts (including members the controller spawns mid-trace)
    # inherit this env: a shared compile cache keeps every warmup after
    # the first a warm start, so arm order and mid-trace spawns measure
    # provisioning economics, not XLA compile variance.
    _os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                           "/tmp/symmetry-tpu-disagg-smoke-cache")
    _os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           "0.3")

    from symmetry_tpu.provider.backends.base import (
        BackendError,
        BackendRestartingError,
        InferenceRequest,
    )
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.utils.metrics import SloMonitor

    def pct(vals, p):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              max(0, -(-p * len(vals) // 100) - 1))], 4)

    # One trace, every arm: the topology is the only variable.
    offsets = arrival_times(arrival, clients, duration_s=duration_s,
                            seed=seed)
    prompts = [(f"req {i:04d} " + "the day curve rises and falls "
                * 64)[:prompt_chars] for i in range(clients)]

    async def run_arm(label: str, m: int, n: int,
                      autoscaled: bool) -> dict:
        tag = _uuid.uuid4().hex[:8]
        backend = TpuNativeBackend(ConfigManager(config={
            "name": f"scale-{label}", "public": False,
            "serverKey": "00" * 32,
            "modelName": f"{preset_name}:scale",
            "apiProvider": "tpu_native",
            "dataCollectionEnabled": False,
            "tpu": {"model_preset": preset_name, "dtype": dtype_name,
                    "max_batch_size": slots, "max_seq_len": max_seq,
                    "prefill_buckets": [bucket],
                    "decode_block": block,
                    "role": "disagg",
                    # Bench-tightened hysteresis (production defaults
                    # are 30s/60s): dwell and cooldown scale down with
                    # the compressed diurnal day, but the spawn
                    # thresholds go UP, not down — one arrival clump in
                    # the 5s fast window must not trigger a mid-trace
                    # boot (whose compile steals the serving cores and
                    # manufactures the very breaches it reacts to).
                    # spawn_burn 1.5 = sustained 1.5x the error budget;
                    # spawn_queue scales with the slot count (2x slots,
                    # sustained): a queue the member batches through in
                    # a couple of waves is throughput, not pressure —
                    # only a backlog beyond that, or measured burn, is
                    # allowed to buy a mid-trace boot.
                    **({"autoscale": {"max_members": max_members,
                                      "dwell_s": 4.0,
                                      "churn_cooldown_s": 15.0,
                                      "spawn_burn": 1.5,
                                      "spawn_queue": max(2.0 * slots,
                                                         4.0),
                                      "spawn_queue_ticks": 8,
                                      "drain_load": 0.25,
                                      "drain_ticks": 12}}
                       if autoscaled else {}),
                    "disagg": {"peer": f"mem://scale-{tag}",
                               "reconnect_base_s": 0.05,
                               "pool": {"prefill": m, "decode": n,
                                        "heartbeat_s": 0.5}}},
        }))
        await backend.start()
        # The REAL sensor: the burn-rate monitor the pool heartbeat
        # hands to the controller, fed with the provider's exact
        # observe calls by the fleet below.
        monitor = SloMonitor({"ttft_s": slo_ttft_s,
                              "inter_chunk_s": slo_chunk_s,
                              "objective": objective,
                              "fast_window_s": 5.0,
                              "slow_window_s": 60.0})
        backend.attach_slo_monitor(monitor)

        per_req: list[dict] = []

        async def one(i: int) -> None:
            await asyncio.sleep(offsets[i])
            row = {"completed": False, "tokens": 0,
                   "ttft": None, "max_gap": None}
            t_send = _time.monotonic()
            t_prev = None
            gaps: list[float] = []
            attempts = 0
            while True:
                try:
                    async for chunk in backend.stream(InferenceRequest(
                            messages=[{"role": "user",
                                       "content": prompts[i]}],
                            max_tokens=max_new, temperature=0.7,
                            seed=i)):
                        if not chunk.text:
                            continue
                        now = _time.monotonic()
                        if row["ttft"] is None:
                            row["ttft"] = now - t_send
                            monitor.observe("ttft", row["ttft"])
                        elif t_prev is not None:
                            gaps.append(now - t_prev)
                            monitor.observe("inter_chunk", gaps[-1])
                        t_prev = now
                        row["tokens"] += len(chunk.text)
                    row["completed"] = True
                    row["max_gap"] = max(gaps, default=0.0)
                    monitor.observe("e2e", _time.monotonic() - t_send)
                except BackendRestartingError as exc:
                    # The provider/client retry loop in miniature:
                    # structured-retryable sheds (member churn,
                    # respawn windows) back off and resend.
                    attempts += 1
                    if attempts <= 6:
                        await asyncio.sleep(exc.retry_after_s or 0.25)
                        continue
                    row["error"] = f"shed x{attempts}: {exc}"
                except BackendError as exc:
                    row["error"] = str(exc)
                break
            per_req.append(row)

        # Chip-second accounting starts HERE: boot warmup is excluded
        # (it would measure arm order and compile-cache state, not
        # provisioning), but members the controller spawns mid-trace
        # pay their whole life — warmup included — inside the window.
        stats0 = await backend.engine_stats()
        chip0 = float(((stats0.get("disagg") or {}).get("pool") or {})
                      .get("chip_seconds") or 0.0)
        t0 = _time.monotonic()
        await asyncio.gather(*[one(i) for i in range(clients)])
        wall = _time.monotonic() - t0
        stats = await backend.engine_stats()
        pool = (stats.get("disagg") or {}).get("pool") or {}
        await backend.stop()

        def good(r: dict) -> bool:
            return (r["completed"] and r["ttft"] is not None
                    and r["ttft"] <= slo_ttft_s
                    and (r["max_gap"] or 0.0) <= slo_chunk_s)

        goods = [r for r in per_req if good(r)]
        tokens = sum(r["tokens"] for r in per_req)
        good_tokens = sum(r["tokens"] for r in goods)
        chip_s = max(
            float(pool.get("chip_seconds") or 0.0) - chip0, 0.0)
        attainment = len(goods) / max(len(per_req), 1)
        asc = pool.get("autoscale") or {}
        ttfts = [r["ttft"] for r in per_req if r["ttft"] is not None]
        gaps = [r["max_gap"] for r in per_req
                if r["max_gap"] is not None]
        return {
            "shape": label, "autoscaled": autoscaled,
            "requests": clients,
            "completed": sum(r["completed"] for r in per_req),
            "failed": sum(not r["completed"] for r in per_req),
            "wall_s": round(wall, 2),
            "tokens": tokens, "good_tokens": good_tokens,
            "slo_attainment": round(attainment, 4),
            "meets_slo": attainment >= objective,
            # The full tail ladder, not just p50/p99: with an
            # attainment objective the SLO verdict pivots on the
            # percentile AT the objective (p90 for 0.9), so the row
            # records where each arm's distribution actually sits.
            "ttft_p50_s": pct(ttfts, 50), "ttft_p90_s": pct(ttfts, 90),
            "ttft_p95_s": pct(ttfts, 95), "ttft_p99_s": pct(ttfts, 99),
            "max_gap_p90_s": pct(gaps, 90),
            "max_gap_p99_s": pct(gaps, 99),
            "chip_seconds": round(chip_s, 2),
            "goodput_tokens_per_chip_s": (round(good_tokens / chip_s, 2)
                                          if chip_s > 0 else None),
            "members_final": pool.get("healthy"),
            **({"scale": {
                    "spawns": asc.get("spawns"),
                    "drains": asc.get("drains"),
                    "rebalances": asc.get("rebalances"),
                    "target": asc.get("target"),
                    "decisions": asc.get("actions", [])}}
               if autoscaled else {}),
        }

    async def main() -> dict:
        arms: dict[str, dict] = {}
        # The autoscaled arm STARTS at the first static shape — the
        # hand-picked constant the pool would otherwise run all day —
        # with the controller closing the loop on it: right-size down
        # through the troughs (floor 1×1), grow back if the trace
        # demands it. The statics are the same shape(s) pinned for the
        # whole trace; the only variable is whether the loop is closed.
        m0, n0 = (int(x) for x in
                  static_shapes[0].lower().split("x"))
        shapes = [("autoscaled", m0, n0, True)]
        for s in static_shapes:
            m, n = (int(x) for x in s.lower().split("x"))
            shapes.append((f"static-{m}x{n}", m, n, False))
        for label, m, n, autoscaled in shapes:
            print(f"[autoscale] arm {label}: {clients} clients, "
                  f"{arrival} trace over {duration_s:g}s",
                  file=sys.stderr)
            arms[label] = await run_arm(label, m, n, autoscaled)
            a = arms[label]
            print(f"[autoscale] arm {label}: attainment "
                  f"{a['slo_attainment']} ({'meets' if a['meets_slo'] else 'MISSES'} "
                  f"SLO), {a['chip_seconds']} chip-s, goodput "
                  f"{a['goodput_tokens_per_chip_s']} tok/chip-s",
                  file=sys.stderr)
        auto = arms["autoscaled"]
        statics = [a for a in arms.values() if not a["autoscaled"]]
        # Compare against the static shapes that also meet the SLOs —
        # a cheaper static arm that misses them is not provisioning,
        # it is failing. If none meet, compare against all.
        comparators = [a for a in statics if a["meets_slo"]] or statics
        best_static = min(comparators, key=lambda a: a["chip_seconds"])
        wins = (auto["meets_slo"]
                and auto["chip_seconds"] < best_static["chip_seconds"])
        return {
            "kind": "autoscale",
            "metric": f"SLO goodput ({preset_name}, {clients} clients, "
                      f"{arrival} arrivals over {duration_s:g}s, "
                      f"ttft<={slo_ttft_s}s gap<={slo_chunk_s}s @ "
                      f"{objective:.0%}, autoscaled from "
                      f"{static_shapes[0]} vs static "
                      f"{','.join(static_shapes)})",
            "value": auto["goodput_tokens_per_chip_s"],
            "unit": "tok/chip-s",
            "goodput_tokens_per_chip_s":
                auto["goodput_tokens_per_chip_s"],
            "arrival": {"kind": arrival, "duration_s": duration_s,
                        "seed": seed},
            "slo": {"ttft_s": slo_ttft_s, "inter_chunk_s": slo_chunk_s,
                    "objective": objective},
            "arms": arms,
            "autoscaled_chip_seconds": auto["chip_seconds"],
            "best_static_chip_seconds": best_static["chip_seconds"],
            "best_static_shape": best_static["shape"],
            "verdict": ("autoscaled-wins" if wins else
                        "static-wins" if auto["meets_slo"] else
                        "autoscaled-misses-slo"),
        }

    return asyncio.new_event_loop().run_until_complete(main())


def run_e2e(preset_name: str, *, clients: int, slots: int, max_new: int,
            prompt_chars: int, max_seq: int, dtype_name: str, block: int,
            quant: str | None, kv_quant: bool, bucket: int,
            stagger_s: float = 0.0, max_queue: int | None = None,
            max_ttft_s: float | None = None, client_procs: int = 1,
            shared_prefix: bool = False,
            prefix_cache_mb: float | None = None,
            speculative: bool = False, draft_k: int = 8,
            fused_dequant: bool = False, trace_out: str | None = None,
            tracing: bool = True, disagg: bool = False,
            disagg_transport: str | None = None,
            disagg_pool: tuple[int, int] | None = None,
            multi_turn: int = 1,
            metrics_out: str | None = None,
            profile_sample: int = 0,
            pipeline_depth: int | None = None,
            arrival: str | None = None,
            arrival_duration_s: float = 45.0,
            arrival_seed: int = 0) -> dict:
    """The NORTH-STAR measurement (BASELINE.json metric): aggregate WIRE
    tok/s and p50/p99 TTFT through the full serving path — server +
    tpu_native provider + N concurrent streaming clients over TCP
    loopback. This is the serving-path analog of the reference's hot loop
    (reference: src/provider.ts:240-258), where the engine-only bench
    (run_bench) measures just the decode kernel underneath it.

    The provider runs as its OWN OS PROCESS (the real deployment shape,
    `python -m symmetry_tpu.provider -c …`). Sharing one process with
    128 clients measured garbage: the engine thread's device syncs starve
    the shared event loop, so every token event flushed at the end and
    TTFT p50 == wall time."""
    import asyncio
    import os
    import statistics
    import subprocess
    import sys
    import tempfile
    import time as _time

    import yaml

    from symmetry_tpu.client.client import ProviderBusyError, SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.tcp import TcpTransport

    model_name = f"{preset_name}:bench"
    server_ident = Identity.from_name("bench-server")

    async def main() -> dict:
        server = SymmetryServer(server_ident, TcpTransport(),
                                ping_interval_s=60.0)
        await server.start("tcp://127.0.0.1:0")

        cfg = {
            "name": "bench-prov",
            "public": True,
            "serverKey": server_ident.public_hex,
            "serverAddress": server.address,
            "modelName": model_name,
            "apiProvider": "tpu_native",
            "dataCollectionEnabled": False,
            "maxConnections": clients + 8,
            "listenHost": "127.0.0.1",
            "privateSeed": __import__("hashlib").blake2b(
                b"bench-prov-seed", digest_size=32).hexdigest(),
            "tpu": {
                "model_preset": preset_name,
                "dtype": dtype_name,
                "quantization": quant,
                "kv_quantization": "int8" if kv_quant else None,
                "max_batch_size": slots,
                "max_seq_len": max_seq,
                "prefill_buckets": [bucket],
                "decode_block": block,
                **({"max_queue": max_queue} if max_queue is not None
                   else {}),
                **({"max_ttft_s": max_ttft_s} if max_ttft_s is not None
                   else {}),
                **({"prefix_cache_mb": prefix_cache_mb}
                   if prefix_cache_mb else {}),
                **({"speculative": {"k_draft": draft_k}}
                   if speculative else {}),
                **({"fused_dequant": True} if fused_dequant else {}),
                # --pipeline-depth: in-flight decode blocks on the
                # scheduler (1 = the pre-pipeline double buffer, the
                # depth A/B baseline; unset = the config default).
                **({"pipeline_depth": pipeline_depth}
                   if pipeline_depth is not None else {}),
                # Disaggregated prefill/decode: the provider runs a
                # prefill host + decode host pair with KV handoff
                # (engine/disagg/); handoff counters land in the JSON's
                # engine.disagg block. --disagg-transport swaps the
                # local pipes for the cross-machine handoff link (an
                # inline prefill node inside the provider process,
                # reached ONLY over the mem:// or tcp:// link).
                **({"role": "disagg"} if disagg else {}),
                # --disagg-pool MxN: the elastic pool (inline prefill
                # members + N local decode hosts, engine/disagg/pool.py)
                # instead of the fixed pair; --disagg-transport picks
                # the member-link transport (memory default).
                **({"disagg": {
                        "peer": ("tcp://127.0.0.1:0"
                                 if disagg_transport == "tcp"
                                 else "mem://bench-disagg"),
                        **({"inline": True} if not disagg_pool else {}),
                        # Pool × --multi-turn: tighten the heartbeat so
                        # gossiped radix summaries land BETWEEN a
                        # session's turns (default 5s would outlive a
                        # short bench window and every turn-2 placement
                        # would score cold).
                        **({"pool": {"prefill": disagg_pool[0],
                                     "decode": disagg_pool[1],
                                     **({"heartbeat_s": 0.5}
                                        if multi_turn > 1 else {})}}
                           if disagg_pool else {})}}
                   if disagg and (disagg_transport or disagg_pool)
                   else {}),
                # Same reason: recompute the gossiped summary faster
                # than the tightened heartbeat asks for it.
                **({"prefix_gossip_s": 0.25}
                   if disagg_pool and multi_turn > 1 else {}),
                # tracing=False empties the engine-side span rings — the
                # A/B knob for proving the recorder's overhead stays
                # under 1% of greedy decode tok/s (--no-trace vs default
                # at otherwise identical settings).
                **({"tracing": False} if not tracing else {}),
                # symprof (utils/devprof.py): 1-in-N completion probes
                # per dispatch kind — per-kind device durations + the
                # dispatch-gap share land in the engine.devprof block.
                **({"profile_sample": profile_sample}
                   if profile_sample else {}),
            },
        }
        # Provider log is ALWAYS captured (round-3 verdict #1: a 6-line
        # log could not explain a 2x-outlier capture); the tail is echoed
        # to stderr after the run. Per-run file — a fixed path would be
        # clobbered by a concurrent bench on the same machine.
        log_path = os.environ.get("BENCH_PROVIDER_LOG")
        if not log_path:
            with tempfile.NamedTemporaryFile(
                    "w", prefix="bench_provider_", suffix=".log",
                    delete=False) as lf:
                log_path = lf.name
        print(f"[bench] provider log: {log_path}", file=sys.stderr)
        log_fh = open(log_path, "w")


        prompts = ["x" * prompt_chars] * clients
        if speculative:
            # Repetition-heavy, code-like prompts (the prompt-lookup
            # drafter's home turf: keyed records whose n-grams recur), run
            # GREEDY — greedy is both the decode-equivalence contract
            # (wave A and wave B must stream identical text) and the
            # regime where a model's own repetitive continuation keeps
            # matching its context.
            unit = "cfg[{0}].key{0} = value{0}; "
            rep = "".join(unit.format(j % 7) for j in range(64))
            prompts = [("repeat the config table verbatim: "
                        + rep)[:prompt_chars]] * clients
        wave_a_prompts = wave_b_prompts = None
        if shared_prefix:
            # Shared-prefix workload: wave A is the UNCACHED comparison
            # (every client's preamble is unique from its first token, so
            # every admission is a full-prefill miss that churns the LRU),
            # wave B is the CACHED path (one shared preamble; the first
            # dispatch populates the store, everyone after hits). Both
            # waves have identical prompt shapes and arrival patterns, so
            # the TTFT delta between them is the prefix cache's doing.
            # The preamble is sized so the shared portion ends exactly at
            # a prefix-align boundary (min(prefill_chunk=256, bucket) —
            # mirrors engine.prefix_align) and the unique tail fits one
            # suffix dispatch.
            align = min(256, bucket)
            shared_tok = align * max(1, (bucket * 3 // 4) // align)
            # ByteTokenizer chat template wraps content as BOS + "user: "
            # (7 ids, part of the SHARED prefix) … "\nassistant: " (12
            # trailing ids that count against the tail room).
            shared_chars = shared_tok - 7
            tail_room = bucket - shared_tok - 12

            def tail(i: int) -> str:
                return f" client {i:04d} asks question {i:04d}."

            if shared_chars < 8 or tail_room < len(tail(0)):
                raise RuntimeError(
                    f"--prompt-len {bucket} too small for shared-prefix "
                    f"mode (needs room for an aligned preamble + tail + "
                    f"chat template)")

            wave_a_prompts = [f"{i:05d}" + "u" * (shared_chars - 5)
                              + tail(i) for i in range(clients)]
            wave_b_prompts = ["s" * shared_chars + tail(i)
                              for i in range(clients)]
        # All sessions handshake BEFORE any chat is sent (barrier below):
        # the burst then measures the SERVING path against truly
        # simultaneous arrivals — the worst case for admission — instead
        # of smearing 128 Noise handshakes into the ramp, which both
        # inflated TTFT with connection setup and made the measurement
        # sensitive to handshake scheduling variance (round-4 finding:
        # identical engine work, 6.2-9.2 s wire ramp across runs).
        ready = asyncio.Event()
        all_connected = asyncio.Event()
        connected = 0
        # Open-loop arrival trace (--arrival): pre-computed send offsets
        # replace the linear stagger ramp — same barrier, shaped release.
        arrivals = (arrival_times(arrival, clients,
                                  duration_s=arrival_duration_s,
                                  seed=arrival_seed)
                    if arrival else None)

        async def run_sharded_fleet(fleet_prompts: list[str],
                                    temperature: float = 0.7,
                                    spec_flag: bool | None = None
                                    ) -> tuple[list, float, float]:
            """The client fleet split over `client_procs` OS processes
            (run_e2e_client_worker), so the measured tails are the
            SERVICE's, not the client event loop's. Returns (results, t0,
            elapsed) with all stamps on the shared CLOCK_MONOTONIC."""
            shards = [list(range(k, clients, client_procs))
                      for k in range(client_procs)]
            shards = [s for s in shards if s]
            t_connect0 = _time.monotonic()
            procs = []
            try:
                for shard in shards:
                    p = await asyncio.create_subprocess_exec(
                        sys.executable, os.path.abspath(__file__),
                        "--e2e-client-worker",
                        stdin=asyncio.subprocess.PIPE,
                        stdout=asyncio.subprocess.PIPE,
                        limit=1 << 26)  # RESULTS line >> 64 KiB default
                    spec = {"server_address": server.address,
                            "server_key_hex": server_ident.public_hex,
                            "model_name": model_name, "indices": shard,
                            "prompts": [fleet_prompts[i] for i in shard],
                            "max_new": max_new,
                            "stagger_s": stagger_s,
                            **({"arrivals": [arrivals[i] for i in shard]}
                               if arrivals is not None else {}),
                            "temperature": temperature,
                            **({"speculative": spec_flag}
                               if spec_flag is not None else {})}
                    p.stdin.write((json.dumps(spec) + "\n").encode())
                    await p.stdin.drain()
                    procs.append(p)

                async def read_until(p, prefix: str) -> str:
                    while True:
                        raw = await p.stdout.readline()
                        if not raw:
                            raise RuntimeError(
                                f"client worker exited before {prefix}")
                        line = raw.decode()
                        if line.startswith(prefix):
                            return line

                counts = await asyncio.gather(*(
                    asyncio.wait_for(read_until(p, "READY"), 120)
                    for p in procs))
                n_conn = sum(int(c.split()[1]) for c in counts)
                print(f"[bench] {n_conn}/{clients} sessions connected "
                      f"across {len(procs)} client processes in "
                      f"{_time.monotonic() - t_connect0:.1f}s; releasing "
                      f"the burst", file=sys.stderr)
                t0 = _time.monotonic()
                for p in procs:
                    p.stdin.write(b"GO\n")
                await asyncio.gather(*(p.stdin.drain() for p in procs))
                payloads = await asyncio.gather(*(
                    read_until(p, "RESULTS ") for p in procs))
            finally:
                for p in procs:
                    if p.returncode is None and p.stdin is not None:
                        p.stdin.close()
            shard_results = [json.loads(pl[len("RESULTS "):])
                             for pl in payloads]
            await asyncio.gather(*(p.wait() for p in procs))
            results = [r for shard in shard_results for r in shard]
            done_ts = [r["t_done"] for r in results
                       if not r.get("rejected")]
            elapsed = (max(done_ts) - t0) if done_ts else 0.0
            return results, t0, elapsed

        # Multi-turn conversation workload (ROADMAP item 5): each client
        # holds ONE session of `multi_turn` turns, re-submitting the full
        # history every turn — the traffic shape where the prefix cache
        # acts as a session cache (turn N's prompt extends turn N-1's
        # prompt + reply, so its aligned prefix is already cached) and
        # where disaggregation + prefix handoff should shine: turn-2+
        # admissions pay only the new tokens. Greedy, so history growth
        # is deterministic per client. Per-turn content is sized so every
        # turn's full prompt still fits the bucket: budget the bucket
        # over the turns, minus the reply and template overhead.
        turn_room = (bucket // multi_turn - max_new - 24
                     if multi_turn > 1 else 0)
        if multi_turn > 1 and turn_room < 8:
            raise RuntimeError(
                f"--multi-turn {multi_turn} does not fit --prompt-len "
                f"{bucket} with --max-new {max_new}: each turn needs "
                f">= 8 chars of user content after the reply and chat "
                f"template (have {turn_room})")

        async def one_client(i: int) -> dict:
            # stagger_s > 0 = steady-operation arrival pattern (one client
            # every stagger_s); 0 = thundering herd (worst-case TTFT).
            # One code path serves both workload shapes: the default is a
            # single turn of prompts[i] (sampled, seeded); multi_turn > 1
            # runs a whole conversation on the session, greedy, growing
            # the history each turn and recording per-turn TTFT.
            nonlocal connected
            client = SymmetryClient(Identity.from_name(f"bench-cli-{i}"),
                                    TcpTransport())
            details = await client.request_provider(
                server.address, server_ident.public_key, model_name)
            session = await client.connect(details)
            connected += 1
            if connected == clients:
                all_connected.set()
            await ready.wait()
            await asyncio.sleep(arrivals[i] if arrivals is not None
                                else i * stagger_s)
            history: list[dict] = []
            turn_ttfts: list[float] = []
            stamps: list[tuple[float, int]] = []  # (arrival, chars)
            cost_blocks: list[dict] = []  # per-turn symledger blocks
            tokens = 0
            t_first_any = None
            t_begin = _time.perf_counter()
            try:
                for turn in range(max(multi_turn, 1)):
                    history.append({
                        "role": "user",
                        "content": (prompts[i] if multi_turn <= 1 else
                                    f"turn {turn}: client {i:04d} asks "
                                    + "m" * max(1, turn_room - 30))})
                    t_send = _time.perf_counter()
                    t_first = None
                    reply: list[str] = []
                    try:
                        async for delta in session.chat(
                                history, max_tokens=max_new,
                                temperature=(0.0 if multi_turn > 1
                                             else 0.7), seed=i):
                            now = _time.perf_counter()
                            if t_first is None and delta:
                                t_first = now
                                if t_first_any is None:
                                    t_first_any = now
                            reply.append(delta)
                            stamps.append((now, len(delta)))
                        tokens += int(
                            (session.last_usage or {}).get("tokens", 0))
                        costs = getattr(session, "last_costs", None)
                        if costs:
                            cost_blocks.append(costs)
                    except ProviderBusyError as exc:
                        # Overload shedding: an explicit, immediate
                        # rejection — the bounded-latency alternative to
                        # unbounded queueing. Counted separately; never
                        # mixed into serving latency.
                        return {"rejected": True,
                                "reject_s": _time.perf_counter() - t_send,
                                "queue_depth": exc.queue_depth}
                    turn_ttfts.append(
                        (t_first or _time.perf_counter()) - t_send)
                    history.append({"role": "assistant",
                                    "content": "".join(reply)})
            finally:
                await session.close()
            t_done = _time.perf_counter()
            return {"ttft": turn_ttfts[0], "e2e": t_done - t_begin,
                    "chars": sum(c for _, c in stamps), "tokens": tokens,
                    "t_first": t_first_any or t_done, "t_done": t_done,
                    "stamps": stamps, "turn_ttfts": turn_ttfts,
                    **({"cost_blocks": cost_blocks} if cost_blocks
                       else {})}

        engine_stats: dict | None = None
        provider_stats: dict | None = None
        metrics_block: dict | None = None
        # Engine build + warmup runs in the provider process (minutes for
        # 8B cold: weight init + XLA compiles); none of it counts toward
        # the measured window. Registration marks readiness. The log fh is
        # closed in the finally — the early-exception paths (provider
        # never registers, client failure) must not leak the fd, and the
        # tail read below needs the buffer flushed.
        try:
            async with _provider_process(cfg, server, model_name,
                                         timeout_s=1800,
                                         stdout=log_fh) as (_proc,
                                                            startup_s):
                print(f"[bench] provider registered after {startup_s:.0f}s "
                      f"(weight init + XLA compile + warmup; excluded from "
                      f"the measured window)", file=sys.stderr)
                async def fetch_engine_block(field: str) -> dict | None:
                    """One stats round-trip, one engine-stats block (the
                    prefix-cache or speculative counters) — used to
                    snapshot cumulative counters between waves."""
                    try:
                        c = SymmetryClient(
                            Identity.from_name("bench-stats-mid"),
                            TcpTransport())
                        details = await c.request_provider(
                            server.address, server_ident.public_key,
                            model_name)
                        s = await c.connect(details)
                        try:
                            stats = await s.stats()
                        finally:
                            await s.close()
                        return (stats.get("engine") or {}).get(field)
                    except Exception as exc:  # noqa: BLE001 — diag only
                        print(f"[bench] mid-run stats fetch failed: "
                              f"{exc!r}", file=sys.stderr)
                        return None

                results_uncached = None
                pc_after_wave_a = None
                results_plain = None
                plain_elapsed = None
                spec_after_wave_a = None
                if speculative:
                    # Wave A: identical prompts with every request opted
                    # OUT of drafting ("speculative": false) — the plain
                    # decode path on the same provider. Wave B: drafting
                    # on. Both greedy, so the text is token-identical and
                    # the tok/s delta is speculation's doing alone.
                    print("[bench] speculative wave A (drafting off, "
                          "plain decode)", file=sys.stderr)
                    results_plain, _t0a, plain_elapsed = \
                        await run_sharded_fleet(prompts, temperature=0.0,
                                                spec_flag=False)
                    spec_after_wave_a = await fetch_engine_block(
                        "speculative")
                    print("[bench] speculative wave B (n-gram drafting + "
                          "batched verify)", file=sys.stderr)
                    results, t0, elapsed = await run_sharded_fleet(
                        prompts, temperature=0.0)
                elif shared_prefix:
                    # Wave A (unique preambles — all misses) runs to
                    # completion, then wave B (shared preamble — hits
                    # after the first dispatch) on the SAME provider.
                    # Headline metrics come from the cached wave; wave A
                    # supplies the same-run uncached comparison. The
                    # prefix counters are SNAPSHOTTED between waves so
                    # the reported cached-wave hit rate is wave B's
                    # delta, not diluted by wave A's intentional misses.
                    print("[bench] shared-prefix wave A (uncached, unique "
                          "preambles)", file=sys.stderr)
                    results_uncached, _t0a, _el_a = await run_sharded_fleet(
                        wave_a_prompts)
                    pc_after_wave_a = await fetch_engine_block(
                        "prefix_cache")
                    print("[bench] shared-prefix wave B (cached, shared "
                          "preamble)", file=sys.stderr)
                    results, t0, elapsed = await run_sharded_fleet(
                        wave_b_prompts)
                elif client_procs > 1 and multi_turn <= 1:
                    results, t0, elapsed = await run_sharded_fleet(prompts)
                else:
                    tasks = [asyncio.ensure_future(one_client(i))
                             for i in range(clients)]
                    # Release the burst only once every session is
                    # connected; a wedged/failed connection surfaces
                    # through the gather below.
                    t_connect0 = _time.perf_counter()
                    done_any = asyncio.ensure_future(
                        asyncio.wait(tasks,
                                     return_when=asyncio.FIRST_EXCEPTION))
                    await asyncio.wait(
                        [asyncio.ensure_future(all_connected.wait()),
                         done_any],
                        timeout=120, return_when=asyncio.FIRST_COMPLETED)
                    connect_s = _time.perf_counter() - t_connect0
                    print(f"[bench] {connected}/{clients} sessions "
                          f"connected in {connect_s:.1f}s; releasing the "
                          f"burst", file=sys.stderr)
                    t0 = _time.perf_counter()
                    ready.set()
                    results = await asyncio.gather(*tasks)
                    elapsed = _time.perf_counter() - t0
                # Engine-side breakdown (scheduler phase counters, engine
                # TTFT, admission dispatch + block-interval percentiles) —
                # fetched while the provider is still up, so the capture
                # can attribute a slow run to engine vs relay/wire.
                try:
                    stats_client = SymmetryClient(
                        Identity.from_name("bench-stats"), TcpTransport())
                    details = await stats_client.request_provider(
                        server.address, server_ident.public_key, model_name)
                    stats_session = await stats_client.connect(details)
                    try:
                        provider_stats = await stats_session.stats()
                        engine_stats = provider_stats.get("engine")
                        # Final metrics-registry snapshot (the stats
                        # reply's tier-labeled `metrics` block): inlined
                        # into the bench JSON so every BENCH_r*.json is
                        # self-describing, and optionally its own file
                        # (--metrics-out) for offline diffing.
                        metrics_block = provider_stats.get("metrics")
                        if metrics_out and metrics_block:
                            with open(metrics_out, "w") as mf:
                                json.dump(metrics_block, mf, indent=1)
                            n_fams = sum(
                                len(s.get("snapshot", {})
                                    .get("families") or {})
                                for s in metrics_block.get("snapshots",
                                                           []))
                            print(f"[bench] metrics snapshot → "
                                  f"{metrics_out} ({n_fams} families)",
                                  file=sys.stderr)
                        if trace_out:
                            # Distributed-trace capture (utils/trace.py):
                            # one traced request measures the session's
                            # provider clock offset AND threads its trace
                            # id through provider → host → scheduler, then
                            # the merged component rings (the whole run's
                            # recent window — this request and the fleet's
                            # tail) export as one Perfetto timeline.
                            # After the stats read so counters above are
                            # unaffected; provider still up.
                            async for _ in stats_session.chat(
                                    [{"role": "user",
                                      "content": "trace capture probe"}],
                                    max_tokens=8, temperature=0.0):
                                pass
                            perfetto = await stats_client.export_trace(
                                stats_session)
                            with open(trace_out, "w") as tf:
                                json.dump(perfetto, tf)
                            comps = {e["args"]["name"]
                                     for e in perfetto["traceEvents"]
                                     if e.get("name") == "process_name"}
                            print(f"[bench] perfetto trace → {trace_out} "
                                  f"({len(perfetto['traceEvents'])} events "
                                  f"from {sorted(comps)})", file=sys.stderr)
                    finally:
                        await stats_session.close()
                except Exception as exc:  # noqa: BLE001 — diagnostics only
                    print(f"[bench] engine stats fetch failed: {exc!r}",
                          file=sys.stderr)
            await server.stop()
        finally:
            log_fh.close()

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        # Shed requests got an explicit busy rejection (bounded-latency
        # admission) — reported separately, excluded from every serving
        # percentile. reject_s records how fast the rejection came back.
        # p99 is the same nearest-rank estimate used everywhere else, not
        # the max it used to be mislabeled as.
        rejected = [r for r in results if r.get("rejected")]
        results = [r for r in results if not r.get("rejected")]
        if rejected:
            rj = sorted(r["reject_s"] for r in rejected)
            print(f"[bench] {len(rejected)}/{clients} requests shed "
                  f"(busy), rejection latency p50/p99 "
                  f"{pct(rj, 0.50):.2f}/{pct(rj, 0.99):.2f}s",
                  file=sys.stderr)
        if not results:
            raise RuntimeError("every request was shed — queue bound too "
                               "tight for this arrival pattern")

        # Exact wire token counts: inferenceEnded carries the engine's
        # per-request totals (ByteTokenizer chars under-count — multi-byte
        # UTF-8 assemblies collapse several byte tokens into one char).
        tokens = sum(r["tokens"] for r in results)
        ttfts = sorted(r["ttft"] for r in results)
        e2es = sorted(r["e2e"] for r in results)

        tok_s = tokens / elapsed

        # Inter-chunk gap p99: the longest stall any active stream saw
        # between consecutive deltas. The admission cap + chunked prefill
        # exist to bound this near one decode-block time — an unbounded
        # value means admissions are freezing active streams.
        gaps: list[float] = []
        for r in results:
            ts = [t for (t, _) in r["stamps"]]
            gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        gaps.sort()
        gap_p99 = pct(gaps, 0.99) if gaps else None

        # STEADY-STATE wire rate: the window where every client is live
        # (after the admission ramp, before the first completion) — the
        # number comparable to the engine-only bench. Char arrivals in
        # the window are scaled to tokens by each client's own
        # tokens/chars ratio.
        t1 = max(r["t_first"] for r in results)
        t2 = min(r["t_done"] for r in results)
        steady_tok_s = None
        if t2 > t1 + 0.5:
            window_tokens = 0.0
            for r in results:
                if not r["chars"]:
                    continue
                ratio = r["tokens"] / r["chars"]
                window_tokens += ratio * sum(
                    c for (t, c) in r["stamps"] if t1 < t <= t2)
            steady_tok_s = window_tokens / (t2 - t1)
        dtype_label = f"{dtype_name}+{quant}" if quant else dtype_name
        if kv_quant:
            dtype_label += "+kv8"
        if fused_dequant:
            dtype_label += "+fused"

        # ------------------------------------------------------------------
        # Per-phase breakdown (round-3 verdict #1): the capture must carry
        # its own explanation. Ramp = burst start → every client streaming;
        # steady = every client live; tail = first completion → last.
        ramp_s = t1 - t0
        steady_s = max(t2 - t1, 0.0)
        tail_s = max(elapsed - (t2 - t0), 0.0)
        phases = {
            "startup_s": round(startup_s, 1),
            "ramp_s": round(ramp_s, 2),
            "steady_s": round(steady_s, 2),
            "tail_s": round(tail_s, 2),
        }
        print(f"[bench] phases: startup {startup_s:.0f}s (excluded) | "
              f"ramp {ramp_s:.1f}s (admission of {clients} prompts) | "
              f"steady {steady_s:.1f}s @ "
              f"{steady_tok_s and round(steady_tok_s) or '?'} tok/s | "
              f"tail {tail_s:.1f}s", file=sys.stderr)

        diag: dict = {}
        ttft_stages = None
        spec_stats = None
        if engine_stats:
            # Three TTFT vantage points bracket any stall: engine (first
            # sampled token), provider (first chunk leaving the backend
            # for the wire), client (first delta received). engine ≈
            # provider << client → the stall is wire/client-loop;
            # provider >> engine → the host→provider relay.
            prov_ttft = (provider_stats or {}).get("ttft_s") or {}
            ttft_h = engine_stats.get("engine_ttft_s") or {}
            admit_h = engine_stats.get("admit_dispatch_s") or {}
            ival_h = engine_stats.get("block_interval_s") or {}
            diag = {
                "provider_ttft_p50_s": _rnd(prov_ttft.get("p50")),
                "provider_ttft_p99_s": _rnd(prov_ttft.get("p99")),
                "engine_ttft_p50_s": _rnd(ttft_h.get("p50")),
                "engine_ttft_p99_s": _rnd(ttft_h.get("p99")),
                "admit_dispatches": engine_stats.get("admit_dispatches"),
                "admit_dispatch_p99_s": _rnd(admit_h.get("p99")),
                "admit_total_s": _rnd(engine_stats.get("admit_s")),
                "block_interval_p50_s": _rnd(ival_h.get("p50")),
                "block_interval_p99_s": _rnd(ival_h.get("p99")),
                "block_syncs": engine_stats.get("block_syncs"),
                "sync_total_s": _rnd(engine_stats.get("sync_s")),
            }
            # Emit-path accounting (block-coalesced host protocol + wire
            # corking): pipe writes per decode block should sit near 1 —
            # O(slots) would mean the batched `events` frame regressed —
            # and wire writes below wire frames means per-peer corking is
            # collapsing the fan-out.
            emit_h = engine_stats.get("emit") or {}
            wire = (provider_stats or {}).get("wire") or {}
            blocks = engine_stats.get("block_syncs") or 0
            if emit_h:
                diag["pipe_writes"] = emit_h.get("pipe_writes")
                diag["pipe_event_writes"] = emit_h.get("pipe_event_writes")
                diag["pipe_events"] = emit_h.get("pipe_events")
                if blocks:
                    # Event-carrying writes only: ready/stats frames are
                    # pipe traffic but not emit-path traffic, and must
                    # not smear the O(1)-writes-per-block reading.
                    diag["pipe_writes_per_block"] = _rnd(
                        (emit_h.get("pipe_event_writes") or 0) / blocks)
            if wire:
                diag["wire_writes"] = wire.get("writes")
                diag["wire_frames"] = wire.get("frames")
                diag["wire_coalesced_frames"] = wire.get("coalesced_frames")
                diag["wire_bytes"] = wire.get("bytes")
            emit_parts = []
            if emit_h:
                wpb = (f" ({diag['pipe_writes_per_block']} writes/block)"
                       if blocks else "")
                emit_parts.append(
                    f"{diag.get('pipe_event_writes')} event pipe writes "
                    f"/ {diag.get('pipe_events')} events over {blocks} "
                    f"blocks{wpb}")
            if wire:
                emit_parts.append(
                    f"wire {diag.get('wire_writes')} writes / "
                    f"{diag.get('wire_frames')} frames "
                    f"({diag.get('wire_coalesced_frames')} corked)")
            if emit_parts:
                print("[bench] emit path: " + " | ".join(emit_parts),
                      file=sys.stderr)
            # Convert-wall metrics (scheduler stats): per-step decode
            # wall + the weight bytes it streams — the decode-floor
            # number now lands in every BENCH_r*.json engine block, not
            # only the engine-only bench (fused-dequant A/B reads it).
            for key in ("decode_step_ms", "weight_bytes_per_step",
                        "weight_stream_gbs", "weight_stream_gbs_per_device"):
                if engine_stats.get(key) is not None:
                    diag[key] = engine_stats[key]
            if diag.get("decode_step_ms") is not None:
                wb = diag.get("weight_bytes_per_step") or 0
                print(f"[bench] decode step {diag['decode_step_ms']} ms | "
                      f"weight stream {wb / 1e6:.0f} MB/step @ "
                      f"{diag.get('weight_stream_gbs')} GB/s effective",
                      file=sys.stderr)
            # Overlapped-scheduler split (round-16): how much of the
            # engine thread's wall was spent on the dispatch loop proper
            # vs work the emit worker absorbed, plus the configured
            # pipeline depth — the A/B number for depth 1 vs 2 rides
            # every BENCH_r*.json engine block.
            if engine_stats.get("pipeline_depth") is not None:
                diag["pipeline_depth"] = engine_stats["pipeline_depth"]
                diag["dispatch_thread_s"] = _rnd(
                    engine_stats.get("dispatch_thread_s"))
                diag["offloaded_s"] = _rnd(engine_stats.get("offloaded_s"))
                dtb = engine_stats.get("dispatch_thread_block_s") or {}
                if dtb:
                    diag["dispatch_thread_block_p50_s"] = _rnd(
                        dtb.get("p50"), 5)
                    diag["dispatch_thread_block_p99_s"] = _rnd(
                        dtb.get("p99"), 5)
                print(f"[bench] pipeline depth "
                      f"{diag['pipeline_depth']} | dispatch thread "
                      f"{diag['dispatch_thread_s']}s | offloaded "
                      f"{diag['offloaded_s']}s | dispatch-thread block "
                      f"p50/p99 {diag.get('dispatch_thread_block_p50_s')}/"
                      f"{diag.get('dispatch_thread_block_p99_s')}s",
                      file=sys.stderr)
            print(
                "[bench] engine: "
                f"ttft p50/p99 {diag['engine_ttft_p50_s']}/"
                f"{diag['engine_ttft_p99_s']}s | provider ttft p50/p99 "
                f"{diag['provider_ttft_p50_s']}/"
                f"{diag['provider_ttft_p99_s']}s | "
                f"{diag['admit_dispatches']} admit dispatches "
                f"(p99 {diag['admit_dispatch_p99_s']}s, "
                f"total {diag['admit_total_s']}s) | "
                f"block interval p50/p99 {diag['block_interval_p50_s']}/"
                f"{diag['block_interval_p99_s']}s over "
                f"{diag['block_syncs']} blocks",
                file=sys.stderr)
            # Per-stage TTFT attribution (round-4 task #3): where the
            # time between client send and first delta actually went —
            # submit (provider→pipe), pipe_in (pipe + host tokenize),
            # queue (scheduler inbox), prefill (placement→first token),
            # emit (block-flush hold), relay (pipe out + provider loop).
            # The FULL per-stage breakdown (not just the printed p50
            # line) rides the final JSON as `ttft_stages`, so BENCH_r*.json
            # captures it for trajectory analysis.
            stages = engine_stats.get("stages") or {}
            if stages:
                order = ("submit", "pipe_in", "queue", "prefill",
                         "emit", "relay")
                diag["stage_p50_s"] = {
                    k: _rnd((stages.get(k) or {}).get("p50"))
                    for k in order if k in stages}
                diag["stage_p99_s"] = {
                    k: _rnd((stages.get(k) or {}).get("p99"))
                    for k in order if k in stages}
                ttft_stages = {
                    k: {m: _rnd(v, 4) for m, v in (stages[k] or {}).items()}
                    for k in order if k in stages}
                print("[bench] ttft stages p50 (s): "
                      + " | ".join(f"{k} {diag['stage_p50_s'][k]}"
                                   for k in order
                                   if k in diag["stage_p50_s"]),
                      file=sys.stderr)
            # Shared-prefix KV cache counters (host stats → provider
            # stats → here): hit rate, reuse volume, eviction churn.
            pc = engine_stats.get("prefix_cache")
            if pc:
                diag["prefix_cache"] = pc
                print(f"[bench] prefix cache: hit rate {pc.get('hit_rate')} "
                      f"({pc.get('hits')} hits / {pc.get('misses')} misses)"
                      f" | {pc.get('tokens_reused')} prefill tokens reused"
                      f" | {pc.get('insertions')} stored, "
                      f"{pc.get('evictions')} evicted, "
                      f"{pc.get('bytes')} / {pc.get('budget_bytes')} bytes",
                      file=sys.stderr)
            # Speculative decoding counters (host stats → provider stats
            # → here): drafted/accepted volume, acceptance rate, and the
            # tokens-per-verify-dispatch distribution.
            spec_stats = engine_stats.get("speculative")
            if spec_stats:
                tpd = spec_stats.get("tokens_per_dispatch") or {}
                print(f"[bench] speculative: "
                      f"{spec_stats.get('verify_blocks')} verify blocks | "
                      f"{spec_stats.get('drafted')} drafted, "
                      f"{spec_stats.get('accepted')} accepted "
                      f"(rate {spec_stats.get('acceptance_rate')}), "
                      f"{spec_stats.get('rolled_back')} rolled back | "
                      f"tokens/dispatch p50/p99 "
                      f"{_rnd(tpd.get('p50'))}/{_rnd(tpd.get('p99'))}",
                      file=sys.stderr)
            # Disaggregation ledger (broker counters + the prefill
            # host's own stats, nested under engine.disagg): handoff
            # frames/bytes, prefill-tier residency percentiles, and the
            # per-tier serialize/adopt walls — the acceptance contract
            # is that these flow host stats → provider stats → HERE.
            dg = engine_stats.get("disagg")
            if dg:
                diag["disagg"] = dg
                pt = dg.get("prefill_tier_s") or {}
                ph = dg.get("prefill_host") or {}
                ho = ph.get("handoff") or {}
                ad = engine_stats.get("adopt") or {}
                # The handoff cost SPLIT as explicit top-level fields
                # (they used to be one opaque number inside nested host
                # stats): serialize = the prefill host's frame-encode
                # wall; wire = emit → broker receipt through the pipe
                # (local pair) or the chunked link (network mode), on
                # reconciled clocks. Link counters (retries, credit
                # stalls) ride when the cross-machine link is in play.
                ws = dg.get("wire_s") or {}
                diag["disagg"]["handoff_serialize_s"] = \
                    ho.get("serialize_s")
                diag["disagg"]["handoff_wire_s_total"] = \
                    dg.get("wire_s_total")
                node = dg.get("node") or {}
                link = dg.get("link") or {}
                if node or link:
                    diag["disagg"]["handoff_wire"] = {
                        "retries": node.get("retries"),
                        "failed": node.get("failed"),
                        "credit_stalls": node.get("credit_stalls"),
                        "credit_stall_s": node.get("credit_stall_s"),
                        "connects": link.get("connects"),
                        "drops": link.get("drops"),
                        "partial_discards": link.get("partial_discards"),
                    }
                # Elastic-pool block (--disagg-pool): per-node
                # membership + placements and the churn ledger
                # (re-placements after any node loss during the run) —
                # the 2×2-vs-1×1 row schema of the pre-registered
                # BASELINE.md pool protocol.
                pool = dg.get("pool")
                if pool:
                    diag["disagg"]["pool"] = pool
                    per_node = {mid: m.get("placements")
                                for mid, m in
                                (pool.get("members") or {}).items()}
                    print(f"[bench] disagg pool: healthy "
                          f"{pool.get('healthy')} | placements "
                          f"{per_node} | re-placements "
                          f"{pool.get('re_placements')} | losses "
                          f"{pool.get('losses')} | drains "
                          f"{pool.get('drains')}", file=sys.stderr)
                print(f"[bench] disagg: {dg.get('handoff_frames')} "
                      f"handoffs / {dg.get('handoff_bytes')} bytes "
                      f"({dg.get('prefix_tokens')} prefix tokens, "
                      f"{dg.get('routing_only')} routing-only) | "
                      f"prefill tier p50/p99 {_rnd(pt.get('p50'))}/"
                      f"{_rnd(pt.get('p99'))}s | serialize "
                      f"{ho.get('serialize_s')}s | wire p50/p99 "
                      f"{_rnd(ws.get('p50'))}/{_rnd(ws.get('p99'))}s "
                      f"(total {_rnd(dg.get('wire_s_total'))}s"
                      + (f", {node.get('retries')} retries, "
                         f"{node.get('credit_stalls')} credit stalls"
                         if node else "")
                      + f") | adopt {ad.get('deserialize_s')}s "
                      f"host-side, "
                      f"{_rnd(engine_stats.get('adopt_s'))}s dispatch",
                      file=sys.stderr)
            # The attribution that mattered in round 3: wire TTFT far above
            # engine TTFT means the stall is relay/wire/client-loop, not
            # admission.
            wire_p50 = pct(ttfts, 0.50)
            eng_p50 = ttft_h.get("p50")
            if eng_p50 and wire_p50 > 2.0 * eng_p50 + 1.0:
                print(f"[bench] WARNING: wire TTFT p50 {wire_p50:.1f}s >> "
                      f"engine TTFT p50 {eng_p50:.1f}s — the gap is in the "
                      f"relay/wire/client loop, not the engine",
                      file=sys.stderr)
        try:
            with open(log_path) as lf:
                tail_lines = lf.readlines()[-8:]
            print("[bench] provider log tail:", file=sys.stderr)
            for ln in tail_lines:
                print(f"  {ln.rstrip()}", file=sys.stderr)
        except OSError:
            pass

        speculative_block = None
        if speculative and results_plain is not None:
            ok_p = [r for r in results_plain if not r.get("rejected")]
            plain_tokens = sum(r["tokens"] for r in ok_p)
            plain_tok_s = (plain_tokens / plain_elapsed
                           if plain_elapsed else None)
            tp = sorted(r["ttft"] for r in ok_p)
            speculative_block = {
                "tok_s_plain": _rnd(plain_tok_s, 1),
                "tok_s_speculative": round(tok_s, 1),
                "speedup": (round(tok_s / plain_tok_s, 3)
                            if plain_tok_s else None),
                "ttft_p50_plain_s": (round(pct(tp, 0.50), 3)
                                     if tp else None),
                "ttft_p50_speculative_s": round(pct(ttfts, 0.50), 3),
            }
            if spec_stats:
                # Wave-B delta: cumulative counters minus the between-
                # waves snapshot. Wave A requests opt out of drafting, so
                # its contribution should be ~0, but the subtraction
                # keeps the quoted numbers honest either way.
                base = spec_after_wave_a or {}
                for key in ("verify_blocks", "drafted", "accepted",
                            "rolled_back", "spec_tokens"):
                    speculative_block[key] = (spec_stats.get(key, 0)
                                              - base.get(key, 0))
                drafted = speculative_block["drafted"]
                speculative_block["acceptance_rate"] = (
                    round(speculative_block["accepted"] / drafted, 4)
                    if drafted else None)
                speculative_block["tokens_per_dispatch"] = (
                    spec_stats.get("tokens_per_dispatch"))
            print(f"[bench] speculative vs plain (same prompts, same "
                  f"provider): {speculative_block['tok_s_plain']} tok/s "
                  f"plain → {speculative_block['tok_s_speculative']} "
                  f"tok/s speculative "
                  f"(x{speculative_block['speedup']})", file=sys.stderr)

        shared_block = None
        if shared_prefix and results_uncached is not None:
            ok_a = [r for r in results_uncached if not r.get("rejected")]
            ta = sorted(r["ttft"] for r in ok_a)
            shared_block = {
                "uncached_admitted": len(ok_a),
                "ttft_p50_uncached_s": (round(pct(ta, 0.50), 3)
                                        if ta else None),
                "ttft_p99_uncached_s": (round(pct(ta, 0.99), 3)
                                        if ta else None),
                "ttft_p50_cached_s": round(pct(ttfts, 0.50), 3),
                "ttft_p99_cached_s": round(pct(ttfts, 0.99), 3),
            }
            pc_end = diag.get("prefix_cache")
            if pc_end:
                # Wave-B delta: cumulative counters minus the between-
                # waves snapshot, so the quoted hit rate is the cached
                # wave's own, undiluted by wave A's intentional misses.
                base = pc_after_wave_a or {}
                d_hits = pc_end.get("hits", 0) - base.get("hits", 0)
                d_miss = pc_end.get("misses", 0) - base.get("misses", 0)
                shared_block["cached_wave_hits"] = d_hits
                shared_block["cached_wave_misses"] = d_miss
                shared_block["hit_rate"] = (
                    round(d_hits / (d_hits + d_miss), 4)
                    if d_hits + d_miss else None)
            if ta:
                print(f"[bench] shared-prefix: TTFT p50 uncached "
                      f"{shared_block['ttft_p50_uncached_s']}s → cached "
                      f"{shared_block['ttft_p50_cached_s']}s (p99 "
                      f"{shared_block['ttft_p99_uncached_s']} → "
                      f"{shared_block['ttft_p99_cached_s']})",
                      file=sys.stderr)

        multi_turn_block = None
        if multi_turn > 1:
            first = sorted(r["turn_ttfts"][0] for r in results
                           if r.get("turn_ttfts"))
            later = sorted(t for r in results
                           for t in r.get("turn_ttfts", [])[1:])
            if first and later:
                # The per-turn TTFT CURVE vs history length — the radix
                # cache's "done" evidence (ROADMAP item 3): every turn's
                # prompt is strictly longer than the last, so a flat or
                # falling curve means admission cost tracks the NEW
                # tokens, not the history.
                by_turn = []
                for t in range(multi_turn):
                    vals = sorted(r["turn_ttfts"][t] for r in results
                                  if len(r.get("turn_ttfts", [])) > t)
                    by_turn.append(round(pct(vals, 0.50), 3)
                                   if vals else None)
                multi_turn_block = {
                    "turns": multi_turn,
                    "sessions": len(results),
                    "ttft_turn1_p50_s": round(pct(first, 0.50), 3),
                    "ttft_turn1_p99_s": round(pct(first, 0.99), 3),
                    "ttft_turn2plus_p50_s": round(pct(later, 0.50), 3),
                    "ttft_turn2plus_p99_s": round(pct(later, 0.99), 3),
                    "ttft_by_turn_p50_s": by_turn,
                    # > 1 means later turns admit faster than turn 1
                    # even though their prompts are LONGER — the session
                    # cache (and, disaggregated, the prefix handoff)
                    # paying for itself.
                    "turn2plus_speedup": (
                        round(pct(first, 0.50) / pct(later, 0.50), 3)
                        if pct(later, 0.50) else None),
                }
                pc = (diag or {}).get("prefix_cache") or {}
                if pc.get("blocks_total"):
                    # Session-cache memory economics: peak pool
                    # occupancy and blocks in use at run end, per the
                    # paged-KV accounting in engine/prefix_cache.py.
                    multi_turn_block["prefix"] = {
                        "block_tokens": pc.get("block_tokens"),
                        "blocks_in_use": pc.get("blocks_in_use"),
                        "blocks_total": pc.get("blocks_total"),
                        "hbm_high_water_bytes": pc.get(
                            "hbm_high_water_bytes"),
                        "hit_rate": pc.get("hit_rate"),
                    }
                print(f"[bench] multi-turn: TTFT p50 turn-1 "
                      f"{multi_turn_block['ttft_turn1_p50_s']}s → "
                      f"turn-2+ "
                      f"{multi_turn_block['ttft_turn2plus_p50_s']}s "
                      f"(x{multi_turn_block['turn2plus_speedup']} though "
                      f"later prompts are longer; p99 "
                      f"{multi_turn_block['ttft_turn1_p99_s']} → "
                      f"{multi_turn_block['ttft_turn2plus_p99_s']})",
                      file=sys.stderr)
                print(f"[bench] multi-turn TTFT p50 by turn: "
                      f"{multi_turn_block['ttft_by_turn_p50_s']}",
                      file=sys.stderr)
                if "prefix" in multi_turn_block:
                    px = multi_turn_block["prefix"]
                    print(f"[bench] prefix pool: "
                          f"{px['blocks_in_use']}/{px['blocks_total']} "
                          f"blocks x {px['block_tokens']} tok, HBM "
                          f"high-water {px['hbm_high_water_bytes']} B, "
                          f"hit rate {px['hit_rate']}", file=sys.stderr)

        # symledger rollup: per-request cost blocks from the end frames
        # (client-observed, so percentiles are over exactly the admitted
        # fleet) + the provider's own SLO-gated goodput window. Absent
        # when tpu.ledger is off — the A/B overhead run's other arm.
        ledger_block = None
        cost_blocks = [r["costs"] for r in results if r.get("costs")]
        for r in results:
            cost_blocks.extend(r.get("cost_blocks") or [])
        if cost_blocks:
            devs = sorted(float(c.get("device_total_s") or 0.0)
                          for c in cost_blocks)
            queues = sorted(float(c.get("queue_s") or 0.0)
                            for c in cost_blocks)
            device = sum(devs)
            wasted = sum(float(c.get("wasted_total_s") or 0.0)
                         for c in cost_blocks)
            saved = sum(float(c.get("saved_s") or 0.0)
                        for c in cost_blocks)
            ctokens = sum(int(c.get("tokens") or 0) for c in cost_blocks)
            ledger_block = {
                "requests": len(cost_blocks),
                "source": cost_blocks[0].get("source"),
                "device_s_p50": round(pct(devs, 0.50), 6),
                "device_s_p99": round(pct(devs, 0.99), 6),
                "device_s_total": round(device, 6),
                "queue_s_p99": round(pct(queues, 0.99), 6),
                "wasted_s_total": round(wasted, 6),
                "wasted_share": (round(wasted / (device + wasted), 4)
                                 if device + wasted > 0 else None),
                "saved_s_total": round(saved, 6),
                "goodput_tokens_per_device_s": (
                    round(ctokens / device, 2) if device > 0 else None),
            }
            gp = (provider_stats or {}).get("goodput")
            if gp:
                # The provider-side verdict (SLO-attaining tokens only)
                # next to the raw client-side ratio above.
                ledger_block["slo_goodput"] = gp
            print(f"[bench] ledger ({ledger_block['source']}): device "
                  f"p50/p99 {ledger_block['device_s_p50']}/"
                  f"{ledger_block['device_s_p99']}s per request | wasted "
                  f"share {ledger_block['wasted_share']} | goodput "
                  f"{ledger_block['goodput_tokens_per_device_s']} "
                  f"tok/device-s", file=sys.stderr)

        return {
            "metric": f"e2e serving tok/s ({preset_name} {dtype_label}, "
                      f"{clients} streaming clients over TCP"
                      + (f" ({arrival} arrivals over "
                         f"{arrival_duration_s:g}s)" if arrival
                         else f" @ {stagger_s}s stagger" if stagger_s
                         else " (burst)")
                      + (", shared-prefix cached wave" if shared_prefix
                         else "")
                      + (f", speculative wave (k={draft_k})" if speculative
                         else "")
                      + ((", disagg "
                          + (f"{disagg_pool[0]}x{disagg_pool[1]} pool"
                             if disagg_pool else "prefill/decode tiers")
                          + (f" over {disagg_transport} link"
                             if disagg_transport else ""))
                         if disagg else "")
                      + (f", {multi_turn}-turn sessions" if multi_turn > 1
                         else "")
                      + f", {max_new} tok/req, {slots} slots, block {block}, "
                        f"provider subprocess, 1 tpu dev)",
            "value": round(tok_s, 1),
            "unit": "tok/s",
            "vs_baseline": round(tok_s / 2000.0, 3),
            "ttft_p50_s": round(pct(ttfts, 0.50), 3),
            "ttft_p99_s": round(pct(ttfts, 0.99), 3),
            "e2e_p50_s": round(pct(e2es, 0.50), 3),
            "e2e_p99_s": round(pct(e2es, 0.99), 3),
            "tokens_streamed": tokens,
            "wall_s": round(elapsed, 2),
            "mean_ttft_s": round(statistics.mean(ttfts), 3),
            "steady_state_tok_s": (round(steady_tok_s, 1)
                                   if steady_tok_s else None),
            "inter_chunk_gap_p99_s": (round(gap_p99, 3)
                                      if gap_p99 is not None else None),
            "phases": phases,
            **({"client_procs": client_procs} if client_procs > 1 else {}),
            **({"arrival": {"kind": arrival,
                            "duration_s": arrival_duration_s,
                            "seed": arrival_seed}}
               if arrival else {}),
            **({"admitted": len(results), "rejected": len(rejected),
                "reject_p99_s": round(pct(rj, 0.99), 3)}
               if rejected else {}),
            **({"shared_prefix": shared_block} if shared_block else {}),
            **({"speculative": speculative_block}
               if speculative_block else {}),
            **({"multi_turn": multi_turn_block} if multi_turn_block
               else {}),
            # symledger rollup: cost percentiles, wasted share, and the
            # goodput row — the capture's attribution headline.
            **({"ledger": ledger_block} if ledger_block else {}),
            # Satellite of the speculative PR: the per-stage TTFT
            # breakdown lands in the JSON capture, not just stderr text.
            **({"ttft_stages": ttft_stages} if ttft_stages else {}),
            **({"engine": diag} if diag else {}),
            # Final metrics-registry snapshot (tier-labeled): the bench
            # artifact carries the fleet-telemetry cut it ended with.
            **({"metrics": metrics_block} if metrics_block else {}),
        }

    return asyncio.new_event_loop().run_until_complete(main())


def run_proxy(*, clients: int, max_new: int, token_delay_s: float) -> dict:
    """The PR1 REFERENCE POINT (BASELINE config 1): the reference's own
    architecture — P2P glue proxying to an external OpenAI-compatible
    HTTP server (reference hot loop: src/provider.ts:240-258). An in-repo
    fake Ollama (tools/fake_ollama.py) stands in for the backend emitting
    instantly, so the measured number is the proxy path's own throughput
    ceiling and per-chunk overhead — the baseline the tpu_native numbers
    are compared against."""
    import asyncio
    import hashlib
    import os
    import statistics
    import subprocess
    import sys
    import tempfile
    import time as _time

    import yaml

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    from fake_ollama import start_server

    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.tcp import TcpTransport

    model_name = "llama3:8b"
    server_ident = Identity.from_name("bench-proxy-server")

    async def main() -> dict:
        backend_runner, backend_port = await start_server(
            "127.0.0.1", 0, token_delay_s)
        server = SymmetryServer(server_ident, TcpTransport(),
                                ping_interval_s=60.0)
        await server.start("tcp://127.0.0.1:0")
        cfg = {
            "name": "bench-proxy-prov",
            "public": True,
            "serverKey": server_ident.public_hex,
            "serverAddress": server.address,
            "modelName": model_name,
            "apiProvider": "ollama",
            "apiProtocol": "http",
            "apiHostname": "127.0.0.1",
            "apiPort": backend_port,
            "apiPath": "/v1/chat/completions",
            "dataCollectionEnabled": False,
            "maxConnections": clients + 8,
            "listenHost": "127.0.0.1",
            "privateSeed": hashlib.blake2b(
                b"bench-proxy-seed", digest_size=32).hexdigest(),
        }
        async def one_client(i: int) -> dict:
            client = SymmetryClient(
                Identity.from_name(f"bench-proxy-cli-{i}"), TcpTransport())
            details = await client.request_provider(
                server.address, server_ident.public_key, model_name)
            session = await client.connect(details)
            t_send = _time.perf_counter()
            t_first = None
            chunks = 0
            try:
                async for delta in session.chat(
                        [{"role": "user", "content": "benchmark prompt"}],
                        max_tokens=max_new):
                    now = _time.perf_counter()
                    if t_first is None and delta:
                        t_first = now
                    chunks += 1
            finally:
                await session.close()
            t_done = _time.perf_counter()
            return {"ttft": (t_first or t_done) - t_send,
                    "e2e": t_done - t_send, "chunks": chunks}

        try:
            async with _provider_process(cfg, server, model_name,
                                         timeout_s=120,
                                         stdout=subprocess.DEVNULL):
                t0 = _time.perf_counter()
                results = await asyncio.gather(
                    *(one_client(i) for i in range(clients)))
                elapsed = _time.perf_counter() - t0
        finally:
            await server.stop()
            await backend_runner.cleanup()

        chunks = sum(r["chunks"] for r in results)
        ttfts = sorted(r["ttft"] for r in results)
        tok_s = chunks / elapsed
        return {
            "metric": f"proxy-path serving tok/s (reference architecture: "
                      f"fake-Ollama SSE backend, {clients} streaming "
                      f"clients over TCP, provider subprocess)",
            "value": round(tok_s, 1),
            "unit": "tok/s",
            "vs_baseline": round(tok_s / 2000.0, 3),
            "ttft_p50_s": round(ttfts[len(ttfts) // 2], 4),
            "ttft_p99_s": round(ttfts[min(len(ttfts) - 1,
                                          int(0.99 * len(ttfts)))], 4),
            "mean_e2e_s": round(statistics.mean(r["e2e"] for r in results), 3),
            "chunks_streamed": chunks,
            "per_chunk_overhead_ms": round(
                1e3 * clients * elapsed / max(chunks, 1), 3),
            "wall_s": round(elapsed, 2),
        }

    return asyncio.new_event_loop().run_until_complete(main())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-safe tiny-model run (verification, not perf)")
    ap.add_argument("--e2e", action="store_true",
                    help="full serving path: server + provider + N "
                         "streaming clients over TCP (north-star metric; "
                         "the DEFAULT when no mode flag is given)")
    ap.add_argument("--engine", action="store_true",
                    help="engine-only decode loop (no serving stack)")
    ap.add_argument("--proxy", action="store_true",
                    help="PR1 reference point: proxy backend against an "
                         "in-repo fake-Ollama SSE server (no TPU)")
    ap.add_argument("--proxy-delay", type=float, default=0.0,
                    help="fake backend's per-chunk delay seconds (--proxy)")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="shared-prefix workload (--e2e): wave A of "
                         "unique-preamble prompts (uncached), then wave B "
                         "sharing one long preamble — the prefix KV cache "
                         "serves wave B's admissions from cached KV and "
                         "the run reports cached vs uncached TTFT on the "
                         "same provider (tpu.prefix_cache_mb)")
    ap.add_argument("--prefix-cache-mb", type=float, default=None,
                    help="shared-prefix KV cache HBM budget in MiB "
                         "(tpu.prefix_cache_mb). Default: 128 in "
                         "--shared-prefix mode, disabled otherwise")
    ap.add_argument("--speculative", action="store_true",
                    help="speculative-decoding workload (--e2e): a "
                         "repetition-heavy greedy workload runs twice on "
                         "one provider with tpu.speculative on — wave A "
                         "opts every request out of drafting (plain "
                         "decode), wave B drafts with n-gram prompt "
                         "lookup and batched verify — and the run reports "
                         "speculative vs plain tok/s plus drafted/"
                         "accepted/acceptance-rate counters")
    ap.add_argument("--draft-k", type=int, default=8,
                    help="draft tokens per slot per verify dispatch "
                         "(tpu.speculative k_draft; --speculative only)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode (--e2e): the "
                         "provider runs a prefill host + decode host "
                         "pair (tpu.role: disagg) with versioned KV "
                         "handoff frames between them; handoff "
                         "frames/bytes and prefill-tier latency land in "
                         "the JSON's engine.disagg block. The disagg "
                         "A/B is this flag on vs off at otherwise "
                         "identical settings")
    ap.add_argument("--disagg-transport", default=None,
                    choices=("memory", "tcp"),
                    help="run the disagg pair over the CROSS-MACHINE "
                         "handoff link (engine/disagg/net.py) instead "
                         "of local pipes: the provider runs the decode "
                         "tier + an inline prefill node joined only by "
                         "the chunked/credit-gated link (memory = "
                         "in-process frame queues, tcp = real loopback "
                         "sockets). Adds handoff wire latency/bytes/"
                         "retries/credit-stalls to the JSON beside the "
                         "serialize wall (--disagg only)")
    ap.add_argument("--disagg-pool", default=None, metavar="MxN",
                    help="elastic M-prefill × N-decode pool (implies "
                         "--disagg): M inline prefill members + N local "
                         "decode hosts joined by per-member handoff "
                         "links (engine/disagg/pool.py), least-loaded "
                         "placement, per-node supervision. Per-node "
                         "placements and churn re-placements land in "
                         "the JSON's engine.disagg.pool block — the "
                         "2x2-vs-1x1 row schema of the BASELINE.md "
                         "pool protocol. Transport from "
                         "--disagg-transport (memory default)")
    ap.add_argument("--chaos", action="store_true",
                    help="kill-under-load robustness bench: arm "
                         "--chaos-seam on provider 1's engine host, run "
                         "the client fleet through chat_failover with "
                         "stream resumption ON then OFF, and report "
                         "wasted-work tokens (regenerated − resumed) "
                         "plus post-kill recovery latency per arm "
                         "(BASELINE.md Round 14). Sized small by "
                         "default (8 clients × 64 tok); --clients/"
                         "--max-new/--preset rescale it")
    ap.add_argument("--chaos-seam", default="host.pipe_write=crash@nth=12",
                    metavar="SEAM=ACTION@TRIGGER",
                    help="the fault armed on provider 1's host for "
                         "--chaos (utils/faults.py grammar). The default "
                         "crash lands a few event frames into the first "
                         "wave at the default chaos shape; retune nth "
                         "for bigger fleets")
    ap.add_argument("--autoscale", action="store_true",
                    help="SLO-goodput autoscaling bench: replay one "
                         "seeded --arrival trace (diurnal default) "
                         "against an autoscaled 1x1 pool (tpu.autoscale "
                         "closed loop, engine/disagg/autoscale.py) and "
                         "each --autoscale-static MxN control in ONE "
                         "invocation; per arm: SLO attainment, "
                         "chip-seconds (Σ member-alive time), and "
                         "goodput = SLO-attaining tokens per "
                         "chip-second (BASELINE.md Round 18). Sized "
                         "small by default (24 clients x 48 tok)")
    ap.add_argument("--autoscale-static", default="1x1,2x1,2x2",
                    metavar="MxN[,MxN...]",
                    help="static control shapes for --autoscale; the "
                         "verdict compares the autoscaled arm's "
                         "chip-seconds against the cheapest control "
                         "that also meets the SLOs")
    ap.add_argument("--autoscale-max-members", type=int, default=2,
                    help="per-tier member ceiling for the autoscaled "
                         "arm (tpu.autoscale.max_members)")
    ap.add_argument("--arrival", default=None,
                    choices=("poisson", "diurnal", "burst"),
                    help="open-loop arrival trace replacing the "
                         "--stagger ramp: seeded per-client send "
                         "offsets over --arrival-duration (poisson = "
                         "memoryless steady load, diurnal = "
                         "trough-peak-trough day curve, burst = 4 "
                         "thundering-herd waves). Works under --e2e "
                         "and --autoscale (where diurnal is the "
                         "default)")
    ap.add_argument("--arrival-duration", type=float, default=45.0,
                    metavar="S",
                    help="window the --arrival trace spans, seconds")
    ap.add_argument("--arrival-seed", type=int, default=0,
                    help="RNG seed for the --arrival trace (same seed "
                         "= same offsets, across runs and arms)")
    ap.add_argument("--slo-ttft", type=float, default=2.5, metavar="S",
                    help="--autoscale TTFT target: a request attains "
                         "its SLO only if first token lands within "
                         "this; also the provider slo: block's ttft_s "
                         "(the burn the controller scales on)")
    ap.add_argument("--slo-chunk", type=float, default=1.5, metavar="S",
                    help="--autoscale inter-chunk gap target "
                         "(slo: inter_chunk_s)")
    ap.add_argument("--slo-objective", type=float, default=0.9,
                    help="fraction of requests that must attain their "
                         "SLOs for an arm to count as meeting them")
    ap.add_argument("--multi-turn", type=int, default=1, metavar="N",
                    help="conversation workload (--e2e): every client "
                         "runs one N-turn session, re-submitting the "
                         "full history each turn, greedy. Reports "
                         "turn-1 vs turn-2+ TTFT — the session-cache "
                         "workload where the prefix cache (enabled by "
                         "default here) and --disagg prefix handoff "
                         "should shine. Runs the inline client fleet "
                         "(client-procs forced to 1)")
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=None,
                    help="decode slots (default 128; 96 in shared-prefix "
                         "mode — the larger prompt bucket plus the cache "
                         "budget must leave the ~95%%-full default HBM "
                         "point some slack)")
    ap.add_argument("--steps", type=int, default=192)
    ap.add_argument("--clients", type=int, default=None,
                    help="concurrent streaming clients (--e2e; default "
                         "128, 96 in shared-prefix mode)")
    ap.add_argument("--stagger", type=float, default=0.0,
                    help="seconds between client arrivals (--e2e); 0 = "
                         "thundering-herd burst, the worst-case TTFT")
    ap.add_argument("--max-new", type=int, default=None,
                    help="tokens per client request (--e2e). Default 480: "
                         "~500 keeps the decode phase dominant over the "
                         "admission ramp, so the aggregate number measures "
                         "serving throughput rather than mostly ramp "
                         "(round-3 verdict #1); 480 exactly fills the 640 "
                         "capacity with the 128 bucket + 2 lookahead blocks")
    ap.add_argument("--prompt-len", type=int, default=None,
                    help="prefill bucket / prompt size (default 128; 384 "
                         "in shared-prefix mode so the shared preamble "
                         "spans a full 256-token alignment boundary)")
    ap.add_argument("--max-seq", type=int, default=None,
                    help="KV capacity per slot. Default 640 = 128-token "
                         "bucket + 480 new tokens + 2 lookahead blocks "
                         "(the scheduler's capacity guard) AND "
                         "128-aligned: a non-multiple-of-128 capacity "
                         "costs ~2 ms/step in the XLA attention path "
                         "(672 vs 640 measured); 704 additionally tripped "
                         "a marginal HBM RESOURCE_EXHAUSTED under a "
                         "simultaneous 128-burst")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=("bfloat16", "float32"))
    ap.add_argument("--mesh-model", type=int, default=1,
                    help="model-axis mesh size (tensor parallelism)")
    ap.add_argument("--block", type=int, default=None,
                    help="decode steps per device dispatch (default: 16 "
                         "for serving — measured same throughput as 64 "
                         "with 2x lower TTFT/inter-chunk latency — and "
                         "64 for --engine/--smoke)")
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    metavar="N",
                    help="decode blocks kept in flight on the device "
                         "(tpu.pipeline_depth). 1 = the pre-pipeline "
                         "double buffer, the A/B baseline; 2 (the config "
                         "default) overlaps host emit/admission under "
                         "device compute. --engine mode pipelines its "
                         "dispatch loop to the same depth and reports "
                         "dispatch_thread_block_s; unset keeps each "
                         "mode's default (1 for --engine/--smoke, config "
                         "default for --e2e)")
    ap.add_argument("--quant", default="int8", choices=("none", "int8"),
                    help="weight quantization")
    ap.add_argument("--kv-quant", default="int8", choices=("none", "int8"),
                    help="KV cache quantization")
    ap.add_argument("--fused-dequant", action="store_true",
                    help="route int8 weight matmuls through the W8A16 "
                         "fused-dequant Pallas kernel (tpu.fused_dequant): "
                         "weights pre-packed to the kernel tile layout, "
                         "dequantized in VMEM inside the double-buffered "
                         "DMA/matmul pipeline. The convert-wall A/B is "
                         "this flag on vs off at otherwise identical "
                         "settings (BASELINE.md decode-floor section)")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="requests allowed to queue beyond the decode "
                         "slots before the provider sheds with a busy "
                         "error (--e2e; default: one full extra wave = "
                         "slots). Small values + --stagger model the "
                         "bounded-latency overload row")
    ap.add_argument("--max-ttft", type=float, default=None,
                    help="TTFT-bounded admission (--e2e): shed when the "
                         "provider's estimated first-token wait exceeds "
                         "this many seconds (tpu.max_ttft_s). Default: "
                         "disabled")
    ap.add_argument("--client-procs", type=int, default=None,
                    help="shard the client fleet over N OS processes so "
                         "wire tails measure the service, not one client "
                         "event loop (default: 8 when clients >= 64, "
                         "else 1)")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a merged Perfetto/Chrome-trace JSON "
                         "(client + provider + host + scheduler spans on "
                         "one reconciled clock) captured from the "
                         "provider at the end of the run (--e2e). Load "
                         "at ui.perfetto.dev; BASELINE.md bench rounds "
                         "attach this artifact")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable the engine-side span rings "
                         "(tpu.tracing=false). The tracing-overhead A/B "
                         "is this flag on vs off at otherwise identical "
                         "settings; acceptance: within 1%% tok/s")
    ap.add_argument("--profile-sample", type=int, default=0, metavar="N",
                    help="symprof device-time attribution "
                         "(tpu.profile_sample): completion-probe every "
                         "Nth engine dispatch of each kind — per-kind "
                         "DEVICE duration p50s and the dispatch-gap "
                         "share land in the JSON's devprof block (and "
                         "the Perfetto export gains the device track). "
                         "0 = off. Probes serialize 1 dispatch in N; "
                         "the overhead A/B (BASELINE.md Round 15) is "
                         "this flag vs 0 at otherwise identical "
                         "settings")
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="write the provider's final metrics-registry "
                         "snapshot (tier-labeled JSON, utils/metrics.py "
                         "shape) beside the run; the same snapshot is "
                         "inlined under the result's `metrics` block "
                         "either way, so BENCH_r*.json artifacts are "
                         "self-describing (--e2e)")
    ap.add_argument("--e2e-client-worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one fleet shard
    args = ap.parse_args()
    if args.e2e_client_worker:
        return run_e2e_client_worker()
    # Per-mode defaults: the shared-prefix workload needs a bucket that
    # spans an alignment boundary plus slack for the cache budget, so its
    # defaults trade a few slots for the bigger bucket; everything else
    # keeps the BENCH_r05-comparable point.
    if args.speculative and args.shared_prefix:
        ap.error("--speculative and --shared-prefix are separate "
                 "two-wave workloads; pick one")
    if args.multi_turn < 1:
        ap.error("--multi-turn must be >= 1")
    if args.multi_turn > 1 and (args.shared_prefix or args.speculative):
        ap.error("--multi-turn is its own workload; drop "
                 "--shared-prefix/--speculative")
    if args.disagg_transport and not args.disagg:
        ap.error("--disagg-transport selects the handoff link for the "
                 "disagg pair; it needs --disagg")
    pool_mn = None
    if args.disagg_pool:
        try:
            m, n = args.disagg_pool.lower().split("x")
            pool_mn = (int(m), int(n))
        except ValueError:
            pool_mn = None
        if pool_mn is None or pool_mn[0] < 1 or pool_mn[1] < 1:
            ap.error("--disagg-pool wants MxN with M,N >= 1 (e.g. 2x2)")
        args.disagg = True  # the pool IS a disagg topology
    if args.chaos:
        # Chaos-mode defaults: a recovery drill, not a throughput run —
        # small fleet, short streams, the default seam's nth tuned to
        # land mid-first-wave at exactly this shape.
        args.clients = args.clients if args.clients is not None else 8
        args.slots = args.slots if args.slots is not None else 4
        args.max_new = args.max_new if args.max_new is not None else 64
        args.prompt_len = (args.prompt_len if args.prompt_len is not None
                           else 128)
        args.max_seq = (args.max_seq if args.max_seq is not None
                        else 384)
    if args.autoscale:
        # Autoscale-mode defaults: topology economics, not throughput —
        # a fleet the 1x1 trough shape serves comfortably but whose
        # diurnal peak overloads it, so the static controls must
        # overprovision to meet the SLOs.
        args.arrival = args.arrival or "diurnal"
        args.clients = args.clients if args.clients is not None else 24
        args.slots = args.slots if args.slots is not None else 4
        args.max_new = args.max_new if args.max_new is not None else 48
        args.prompt_len = (args.prompt_len if args.prompt_len is not None
                           else 128)
        args.max_seq = (args.max_seq if args.max_seq is not None
                        else 384)
        for s in args.autoscale_static.split(","):
            parts = s.lower().split("x")
            if (len(parts) != 2 or not all(p.isdigit() for p in parts)
                    or int(parts[0]) < 1 or int(parts[1]) < 1):
                ap.error(f"--autoscale-static wants MxN[,MxN...] with "
                         f"M,N >= 1, got {s!r}")
    if args.clients is None:
        args.clients = (32 if args.multi_turn > 1
                        else 96 if (args.shared_prefix or args.speculative)
                        else 128)
    if args.slots is None:
        args.slots = (32 if args.multi_turn > 1
                      else 96 if (args.shared_prefix or args.speculative)
                      else 128)
    user_prompt_len = args.prompt_len
    if args.prompt_len is None:
        # Multi-turn: the LAST turn's full history must fit the bucket,
        # and turn-2+ hits need each turn to cross a 256-token alignment
        # boundary — 2048 leaves ~512 tokens of budget per turn at the
        # default 4 turns.
        args.prompt_len = (2048 if args.multi_turn > 1
                           else 384 if args.shared_prefix else 128)
    if ((args.shared_prefix or args.multi_turn > 1)
            and args.prefix_cache_mb is None):
        args.prefix_cache_mb = 128.0
    if args.multi_turn > 1:
        # Per-turn TTFT stamps come from the inline fleet; the sharded
        # worker protocol only carries whole-request results.
        args.client_procs = 1
    if args.client_procs is None:
        args.client_procs = 8 if args.clients >= 64 else 1
    user_block = args.block
    if args.block is None:
        args.block = 64 if (args.engine or args.smoke) else 16
    # Track whether the caller sized the run explicitly: the e2e failure
    # ladder only swaps in its conservative point for DEFAULT-sized runs
    # (prompt-len and block participate — the retry point's capacity
    # arithmetic assumes the default 128-token bucket and block 16;
    # shared-prefix mode always counts as sized — its retry point would
    # not fit the preamble).
    user_sized = (args.max_seq is not None or args.max_new is not None
                  or user_prompt_len is not None or user_block is not None
                  or args.shared_prefix or args.speculative
                  or args.multi_turn > 1)
    if args.max_new is None:
        # Speculative mode trims the per-request budget like shared-prefix:
        # two waves on one provider must fit the same wall budget.
        # Multi-turn trims further: every turn's reply re-enters the
        # next turn's prompt, so the reply budget trades against turns.
        args.max_new = (96 if args.multi_turn > 1
                        else 192 if (args.shared_prefix or args.speculative)
                        else 480)
    if args.max_seq is None:
        if args.multi_turn > 1:
            # Bucket + one reply + lookahead, rounded up to 128 (the
            # measured XLA-attention alignment sweet spot).
            need = args.prompt_len + args.max_new + 2 * args.block
            args.max_seq = -(-need // 128) * 128
        else:
            args.max_seq = 640

    def engine_bench() -> dict:
        # engine numbers are recorded at block 64; when the user didn't
        # choose a block, the e2e-failure fallback must not inherit the
        # serving default and measure an incomparable configuration
        return run_bench(args.preset, slots=args.slots, steps=args.steps,
                         prompt_len=args.prompt_len, max_seq=args.max_seq,
                         dtype_name=args.dtype, mesh_model=args.mesh_model,
                         block=64 if user_block is None else user_block,
                         quant=None if args.quant == "none" else args.quant,
                         kv_quant=args.kv_quant == "int8",
                         fused_dequant=args.fused_dequant,
                         profile_sample=args.profile_sample,
                         pipeline_depth=args.pipeline_depth or 1)

    # Capture identity (stamp_result): the RESOLVED knobs that shape the
    # measurement — benchdiff refuses to diff two captures whose
    # fingerprints disagree. Per MODE on purpose: a knob the measured
    # path ignores must not enter the stamp, or two identical
    # measurements launched with different inert flags false-refuse
    # (the exact garbage-delta class the guard exists to stop).
    # Branches that measure a DIFFERENT point than requested (the
    # conservative e2e retry, the engine-only fallback) rebuild
    # `mode`/`fp_cfg` so the stamp describes what actually ran.
    mode = ("smoke" if args.smoke else "chaos" if args.chaos
            else "autoscale" if args.autoscale
            else "engine" if args.engine else "proxy" if args.proxy
            else "e2e")

    def engine_fp(preset: str, slots: int, steps: int, prompt_len: int,
                  max_seq: int, dtype: str, block: int, mesh_model: int,
                  quant, kv_quant, fused_dequant: bool,
                  pipeline_depth: int = 1) -> dict:
        return {"preset": preset, "slots": slots, "steps": steps,
                "prompt_len": prompt_len, "max_seq": max_seq,
                "dtype": dtype, "block": block, "mesh_model": mesh_model,
                "quant": quant, "kv_quant": kv_quant,
                "fused_dequant": fused_dequant,
                "pipeline_depth": pipeline_depth,
                "profile_sample": args.profile_sample}

    if mode == "smoke":
        fp_cfg = engine_fp("tiny", 2, 8, 16, 64, "float32", 2, 1,
                           None, None, False,
                           pipeline_depth=args.pipeline_depth or 1)
    elif mode == "chaos":
        fp_cfg = {"preset": args.preset, "clients": args.clients,
                  "slots": args.slots, "max_new": args.max_new,
                  "prompt_len": args.prompt_len, "max_seq": args.max_seq,
                  "dtype": args.dtype, "block": args.block,
                  "chaos_seam": args.chaos_seam}
    elif mode == "autoscale":
        fp_cfg = {"preset": args.preset, "clients": args.clients,
                  "slots": args.slots, "max_new": args.max_new,
                  "prompt_len": args.prompt_len, "max_seq": args.max_seq,
                  "dtype": args.dtype, "block": args.block,
                  "arrival": args.arrival,
                  "arrival_duration": args.arrival_duration,
                  "arrival_seed": args.arrival_seed,
                  "slo_ttft": args.slo_ttft,
                  "slo_chunk": args.slo_chunk,
                  "slo_objective": args.slo_objective,
                  "static_shapes": args.autoscale_static,
                  "max_members": args.autoscale_max_members}
    elif mode == "engine":
        fp_cfg = engine_fp(args.preset, args.slots, args.steps,
                           args.prompt_len, args.max_seq, args.dtype,
                           args.block, args.mesh_model, args.quant,
                           args.kv_quant, args.fused_dequant,
                           pipeline_depth=args.pipeline_depth or 1)
    elif mode == "proxy":
        fp_cfg = {"clients": args.clients, "max_new": args.max_new,
                  "proxy_delay": args.proxy_delay}
    else:
        fp_cfg = {
            "preset": args.preset, "slots": args.slots,
            "clients": args.clients, "max_new": args.max_new,
            "prompt_len": args.prompt_len, "max_seq": args.max_seq,
            "dtype": args.dtype, "block": args.block,
            "quant": args.quant, "kv_quant": args.kv_quant,
            "fused_dequant": args.fused_dequant,
            "pipeline_depth": args.pipeline_depth,
            "shared_prefix": args.shared_prefix,
            "prefix_cache_mb": args.prefix_cache_mb,
            "speculative": args.speculative,
            "draft_k": args.draft_k if args.speculative else None,
            "disagg": args.disagg,
            "disagg_transport": args.disagg_transport,
            "disagg_pool": args.disagg_pool,
            "multi_turn": args.multi_turn, "stagger": args.stagger,
            **({"arrival": args.arrival,
                "arrival_duration": args.arrival_duration,
                "arrival_seed": args.arrival_seed}
               if args.arrival else {}),
            "max_queue": args.max_queue, "max_ttft": args.max_ttft,
            "client_procs": args.client_procs,
            "tracing": not args.no_trace,
            "profile_sample": args.profile_sample,
        }
    if args.smoke:
        # Smoke mode must not touch a TPU: pin the CPU backend before any
        # jax usage (env alone can be overridden by site hooks).
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_bench("tiny", slots=2, steps=8, prompt_len=16,
                           max_seq=64, dtype_name="float32", mesh_model=1,
                           block=2, profile_sample=args.profile_sample,
                           pipeline_depth=args.pipeline_depth or 1)
    elif args.chaos:
        result = run_chaos(
            args.preset, clients=args.clients, slots=args.slots,
            max_new=args.max_new,
            prompt_chars=max(1, args.prompt_len - 24),
            max_seq=args.max_seq, dtype_name=args.dtype,
            block=args.block, bucket=args.prompt_len,
            seam=args.chaos_seam)
    elif args.autoscale:
        result = run_autoscale(
            args.preset, clients=args.clients, slots=args.slots,
            max_new=args.max_new,
            prompt_chars=max(1, args.prompt_len - 24),
            max_seq=args.max_seq, dtype_name=args.dtype,
            block=args.block, bucket=args.prompt_len,
            arrival=args.arrival, duration_s=args.arrival_duration,
            seed=args.arrival_seed, slo_ttft_s=args.slo_ttft,
            slo_chunk_s=args.slo_chunk, objective=args.slo_objective,
            static_shapes=tuple(args.autoscale_static.split(",")),
            max_members=args.autoscale_max_members)
    elif args.engine:
        result = engine_bench()
    elif args.proxy:
        result = run_proxy(clients=args.clients, max_new=args.max_new,
                           token_delay_s=args.proxy_delay)
    else:
        # Default = the north-star serving measurement (round-2 verdict
        # item 1: wire tok/s + TTFT percentiles). Failure ladder: the
        # 640-ctx point runs the chip ~95% HBM-full, and the effective
        # headroom VARIES across runs on the shared tunnel (identical
        # configs measured green 6x then RESOURCE_EXHAUSTED at first
        # traffic) — so a failed run retries ONCE at an HBM-conservative
        # point (512 ctx / 352 tok/req, ~1.1 GB more slack, still well
        # over baseline) before the engine-only fallback. The scoreboard
        # must never be empty, and should stay an e2e number if at all
        # possible.
        def e2e_attempt(max_seq: int, max_new: int) -> dict:
            return run_e2e(
                args.preset, clients=args.clients, slots=args.slots,
                # ~24 tokens of headroom for the chat template + BOS so
                # the rendered prompt still fits the --prompt-len bucket
                max_new=max_new,
                prompt_chars=max(1, args.prompt_len - 24),
                max_seq=max_seq, dtype_name=args.dtype,
                block=args.block,
                quant=None if args.quant == "none" else args.quant,
                kv_quant=args.kv_quant == "int8", bucket=args.prompt_len,
                stagger_s=args.stagger, max_queue=args.max_queue,
                max_ttft_s=args.max_ttft, client_procs=args.client_procs,
                shared_prefix=args.shared_prefix,
                prefix_cache_mb=args.prefix_cache_mb,
                speculative=args.speculative, draft_k=args.draft_k,
                fused_dequant=args.fused_dequant,
                trace_out=args.trace_out, tracing=not args.no_trace,
                disagg=args.disagg,
                disagg_transport=args.disagg_transport,
                disagg_pool=pool_mn,
                multi_turn=args.multi_turn,
                metrics_out=args.metrics_out,
                profile_sample=args.profile_sample,
                pipeline_depth=args.pipeline_depth,
                arrival=args.arrival,
                arrival_duration_s=args.arrival_duration,
                arrival_seed=args.arrival_seed)

        try:
            result = e2e_attempt(args.max_seq, args.max_new)
        except Exception as exc:  # noqa: BLE001 — scoreboard must not be empty
            print(f"e2e serving bench failed ({exc!r})", file=sys.stderr)
            result = None
            if not user_sized:
                # 512 = prompt bucket (128) + max_new + 2 lookahead
                # blocks; derived so the scheduler's capacity guard never
                # silently truncates the retry's streams.
                cons_new = 512 - args.prompt_len - 2 * args.block
                print(f"[bench] retrying once at the HBM-conservative "
                      f"point (512 ctx / {cons_new} tok/req)",
                      file=sys.stderr)
                try:
                    result = e2e_attempt(512, cons_new)
                    # The retry measured a different point: stamp it as
                    # one (benchdiff must not diff it against the
                    # default-point baseline as same-config).
                    mode = "e2e-conservative"
                    fp_cfg.update(max_seq=512, max_new=cons_new)
                except Exception as exc2:  # noqa: BLE001
                    print(f"conservative e2e retry failed ({exc2!r})",
                          file=sys.stderr)
            if result is None:
                print("falling back to engine-only", file=sys.stderr)
                result = engine_bench()
                mode = "engine-fallback"
                # Rebuild from the knobs engine_bench actually honors —
                # e2e-only flags (clients, stagger, queue bounds, the
                # mode workloads) did not shape this measurement.
                fp_cfg = engine_fp(
                    args.preset, args.slots, args.steps,
                    args.prompt_len, args.max_seq, args.dtype,
                    64 if user_block is None else user_block,
                    args.mesh_model, args.quant, args.kv_quant,
                    args.fused_dequant)
    stamp_result(result, fp_cfg, mode)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
