"""Serving benchmark: aggregate decode throughput of the tpu_native engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline is measured against the BASELINE.json north-star target of
2000 tok/s aggregate (llama3:8b streaming on v5e-8 — reference publishes no
numbers of its own, SURVEY §6, so the target is the yardstick).

Modes:
  python bench.py            # real chip: llama3.2-1b-shaped model, bf16
  python bench.py --smoke    # CPU-safe tiny model (used by /verify)
  python bench.py --preset llama3-8b --slots 16 --steps 256 ...
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run_bench(preset_name: str, *, slots: int, steps: int, prompt_len: int,
              max_seq: int, dtype_name: str, mesh_model: int,
              block: int = 1, quant: str | None = None,
              kv_quant: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, param_logical_axes, preset
    from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype_name]
    config = preset(preset_name)

    if mesh_model > 1:
        mesh = build_mesh(MeshSpec(data=1, model=mesh_model))
        params = jax.device_put(
            init_params(config, jax.random.key(0), dtype),
            shardings_for(param_logical_axes(config), mesh))
        # Quantize AFTER placement: the dense sharding tree doesn't
        # prefix-match QuantizedTensor leaves; jitted quantize preserves
        # input shardings.
        if quant == "int8":
            from symmetry_tpu.models.llama import quantize_params

            params = quantize_params(params)
    else:
        mesh = None
        # Single chip: init leaves directly in int8 so models whose bf16
        # form exceeds HBM (llama3-8b on v5e) still fit.
        params = init_params(config, jax.random.key(0), dtype,
                             quantize=quant == "int8")

    engine = InferenceEngine(
        config, params, ByteTokenizer(), mesh=mesh, max_slots=slots,
        max_seq_len=max_seq, prefill_buckets=(prompt_len,),
        cache_dtype=dtype, decode_block=block, kv_quant=kv_quant)

    # Compile the decode program BEFORE inserting real requests (warmup's
    # garbage device writes are only harmless pre-insert).
    engine.warmup()

    prompt = list(range(1, prompt_len + 1))
    t_prefill0 = time.perf_counter()
    group = max(engine.PREFILL_BATCHES)
    for start in range(0, slots, group):
        engine.prefill_and_insert_many(
            [(slot, [p % 200 for p in prompt],
              SamplingParams(temperature=0.7, seed=slot))
             for slot in range(start, min(start + group, slots))])
    prefill_s = time.perf_counter() - t_prefill0

    # One warm dispatch, then measure. `steps` counts decode steps; each
    # dispatch advances `block` of them.
    engine.decode_steps()
    n_disp = max(1, steps // block)
    t0 = time.perf_counter()
    for _ in range(n_disp):
        engine.decode_steps()  # np.asarray inside = host sync per block
    dt = time.perf_counter() - t0

    done_steps = n_disp * block
    tok_s = slots * done_steps / dt
    dtype_label = f"{dtype_name}+{quant}" if quant else dtype_name
    if kv_quant:
        dtype_label += "+kv8"
    dtype_name = dtype_label
    return {
        "metric": f"aggregate decode tok/s ({preset_name} {dtype_name}, "
                  f"{slots} slots, block {block}, "
                  f"{jax.device_count()} {jax.default_backend()} dev)",
        "value": round(tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / 2000.0, 3),
        "per_slot_tok_s": round(tok_s / slots, 1),
        "prefill_s_per_slot": round(prefill_s / slots, 3),
        "decode_step_ms": round(1e3 * dt / done_steps, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-safe tiny-model run (verification, not perf)")
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=128)
    ap.add_argument("--steps", type=int, default=192)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--max-seq", type=int, default=640)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=("bfloat16", "float32"))
    ap.add_argument("--mesh-model", type=int, default=1,
                    help="model-axis mesh size (tensor parallelism)")
    ap.add_argument("--block", type=int, default=64,
                    help="decode steps per device dispatch")
    ap.add_argument("--quant", default="int8", choices=("none", "int8"),
                    help="weight quantization")
    ap.add_argument("--kv-quant", default="int8", choices=("none", "int8"),
                    help="KV cache quantization")
    args = ap.parse_args()

    if args.smoke:
        # Smoke mode must not touch a TPU: pin the CPU backend before any
        # jax usage (env alone can be overridden by site hooks).
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_bench("tiny", slots=2, steps=8, prompt_len=16,
                           max_seq=64, dtype_name="float32", mesh_model=1,
                           block=2)
    else:
        result = run_bench(args.preset, slots=args.slots, steps=args.steps,
                           prompt_len=args.prompt_len, max_seq=args.max_seq,
                           dtype_name=args.dtype, mesh_model=args.mesh_model,
                           block=args.block,
                           quant=None if args.quant == "none" else args.quant,
                           kv_quant=args.kv_quant == "int8")
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
