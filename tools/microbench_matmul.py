"""Isolate the decode matmul's HBM efficiency on the real chip.

The decode step is weight-read-bound; profile_decode measured the trunk's
effective weight bandwidth at ~480 GB/s — well under v5e's ~819 GB/s. This
benchmarks ONE weight matmul shape in isolation, looping inside a single
jit (scan) so per-dispatch tunnel overhead amortizes away and the weight
(sized past VMEM) must be re-streamed from HBM every iteration.

Variants:
  bf16      x[bf16] @ W[bf16]
  int8      x[bf16] @ W[int8] via ops/quant.qmatmul (mixed dot_general)
  int8-deq  x[bf16] @ dequant(W) materialized per call (the anti-pattern)
  w8a8      per-row-quantized x[int8] @ W[int8], s32 accumulate

Run: python tools/microbench_matmul.py
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_util import sync, timeit  # noqa: E402


def main():
    B, E, H = 128, 4096, 4 * 14336  # W sized ~235 MB int8: past VMEM
    ITERS = 20

    x = jnp.ones((B, E), jnp.bfloat16)
    wf = jax.random.normal(jax.random.key(0), (E, H), jnp.float32)
    w_bf16 = wf.astype(jnp.bfloat16)
    from symmetry_tpu.ops.quant import quantize

    w_q = quantize(wf)
    del wf

    def loop(body):
        """ITERS dependent matmuls in ONE jit; each re-reads W from HBM."""
        def run(x, w):
            def step(carry, _):
                y = body(carry, w)
                # feed a slice of y back so iterations can't be collapsed
                return carry + y[:, :E].astype(carry.dtype) * 1e-6, ()
            out, _ = jax.lax.scan(step, x, None, length=ITERS)
            return out
        return jax.jit(run)

    def report(name, ms, nbytes):
        gbs = nbytes * ITERS / (ms / 1e3) / 1e9
        print(f"{name:10s} {ms:8.2f} ms/loop  {gbs:7.1f} GB/s effective",
              flush=True)

    # bf16 reference
    f = loop(lambda x, w: x @ w)
    report("bf16", timeit(f, x, w_bf16), 2 * E * H)

    # int8 mixed dot (the serving path)
    from symmetry_tpu.ops.quant import qmatmul

    f = loop(qmatmul)
    report("int8", timeit(f, x, w_q), E * H)

    # int8 dequant-materialize (anti-pattern control)
    def deq(x, w):
        return x @ (w.q.astype(jnp.bfloat16) * w.scale.astype(jnp.bfloat16))

    f = loop(deq)
    report("int8-deq", timeit(f, x, w_q), E * H)

    # w8a8: dynamic per-row activation quant, s8 x s8 -> s32 MXU
    def w8a8(x, w):
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
        xs = jnp.maximum(amax, 1e-8) / 127.0
        xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs),
                      -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            xq, w.q, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * xs * w.scale).astype(x.dtype)

    f = loop(w8a8)
    report("w8a8", timeit(f, x, w_q), E * H)

    # int8 with bf16 accumulate hint
    def int8_bf16(x, w):
        y = jax.lax.dot_general(
            x, w.q, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.bfloat16)
        return y * w.scale.astype(jnp.bfloat16)

    f = loop(int8_bf16)
    report("int8-bf16", timeit(f, x, w_q), E * H)

    # int8 TRANSPOSED layout: W stored [out, in], contract on dim 1 of both
    wt = jnp.asarray(np.asarray(w_q.q).T)  # [H, E] int8, materialized
    sc = w_q.scale

    def int8_t(x, wt):
        y = jax.lax.dot_general(
            x, wt, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (y * sc).astype(x.dtype)

    f = loop(int8_t)
    report("int8-T", timeit(f, x, wt), E * H)

    # upcast whole W first with one convert op, then bf16 matmul
    def upcast_first(x, w):
        wb = jax.lax.convert_element_type(w.q, jnp.bfloat16)
        return (x @ wb) * sc.astype(jnp.bfloat16)

    f = loop(upcast_first)
    report("int8-up", timeit(f, x, w_q), E * H)


if __name__ == "__main__":
    main()
