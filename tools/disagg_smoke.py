"""CI disagg smoke: two-host prefill→decode handoff, then a prefill
crash that completes via the restarting-shed/failover path, then the
CROSS-MACHINE link variant with an injected mid-handoff link drop.

The provider's tpu_native backend runs in `tpu.role: disagg` — a REAL
prefill engine host and a REAL decode engine host (tiny CPU preset, own
OS processes, JSON-lines pipes) with versioned KV handoff frames between
them — and the smoke asserts:

  phase 1 (happy path): a streamed request completes; the engine stats
  carry the handoff ledger (frames/bytes > 0, the decode host reporting
  role "decode" with ZERO admission-prefill dispatches, the prefill
  host nested with role "prefill" and its serialize counters) — the
  host stats → provider stats contract of the acceptance criteria,
  end to end through real pipes.

  phase 2 (fault injection): `disagg.handoff=crash@nth=2` is armed in
  the PREFILL tier only (per-tier faults via tpu.disagg.prefill.faults)
  — the second request's handoff kills the prefill host mid-request,
  with the prompt's KV built but unshipped. The in-flight stream must
  get the retryable restarting shed, the supervisor must respawn the
  PAIR (exactly one restart, circuit breaker closed), and a retry must
  complete on the new pair. (nth=2 counts per host LIFE: life 1 serves
  request 1 then dies on request 2's handoff; life 2 serves the retry —
  its first handoff — untouched.)

  phase 3 (TCP link chaos, always runs after either mode): the backend
  runs in NETWORK mode (`tpu.disagg.peer` + inline PrefillNode) — the
  tiers connected ONLY through the chunked/credit-gated handoff link
  over real TCP loopback (engine/disagg/net.py). Request 1 proves the
  happy path and the wire-split stats (wire_frames/wire_s beside the
  prefill host's serialize_s). Then `disagg.net.drop_link=drop_frame@
  nth=2` cuts the link mid-handoff on request 2: the decode tier must
  DISCARD the partial transfer (zero partial adoptions — the decode
  host's adopt error counter stays 0), shed the in-flight request
  structured-retryable, reconnect with backoff, and complete the retry
  on the re-established link.

  phase 4 (pool churn): a 2×1 elastic pool loses a prefill node under
  load; everything completes via retryable shed + re-placement.

  phase 5 (cache affinity): a 2×2 pool serves a multi-turn session —
  turn 2 must affinity-route back to the member whose gossiped radix
  summary covers the session prefix (counter asserted), the per-member
  shipped-block ledger must make the warm handoff partial, and killing
  the warm member must degrade to a clean cold re-place.

Two modes for phases 1–2, same contracts:
  - full path (default): client → server → provider over the in-memory
    transport, recovery via client failover (ChatRestart sentinel);
  - backend-direct (fallback when the `cryptography` network dependency
    is absent): TpuNativeBackend driven directly, recovery via the
    BackendRestartingError retry loop the provider/client implement.

Exit 0 on success; exit 1 with a reason otherwise.

Run: python tools/disagg_smoke.py
"""

from __future__ import annotations

import asyncio
import os
import sys

# CPU pinning + shared compile cache BEFORE any jax import (the engine
# hosts inherit this environment; the cache makes the post-crash respawn
# a warm start, which is also what keeps this smoke affordable).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/symmetry-tpu-disagg-smoke-cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Sized to fit the 64 bucket with the byte-tokenizer chat template
# (~19 ids) while spanning >= 2 alignment boundaries (align 16), so the
# handoff carries real KV and the decode tier admits through adoption.
PROMPT = "tell me about disagg serving"


def provider_config_dict() -> dict:
    return {
        "name": "disagg-smoke-prov", "public": True,
        "serverKey": "00" * 32,
        "modelName": "tiny:disagg", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "flightRecorder": {"enabled": False},
        "tpu": {
            "model_preset": "tiny", "dtype": "float32",
            "max_batch_size": 4, "max_seq_len": 128,
            "prefill_buckets": [32, 64], "prefill_chunk": 16,
            "role": "disagg",
            "supervisor": {"heartbeat_s": 2.0, "wedge_timeout_s": 5.0,
                           "backoff_base_s": 0.2, "backoff_max_s": 1.0,
                           "max_respawns": 3, "spawn_timeout_s": 300.0,
                           "stop_grace_s": 5.0, "min_stable_s": 0.5},
            # Per-tier fault: the PREFILL host's THIRD handoff crashes
            # it (phase 2 — handoffs 1 and 2 are phase 1's cold request
            # and phase 1b's warm block-manifest request); the decode
            # host is never armed.
            "disagg": {"prefill": {
                "faults": {"disagg.handoff": "crash@nth=3"}}},
        },
    }


# Phase 1b: a SECOND request that extends PROMPT — it shares every
# whole block of the first request's prefix, so its handoff frame must
# ship only the non-resident tail blocks (the shared ones ride the
# digest manifest and are adopted by reference on the decode tier).
PROMPT_WARM = PROMPT + " blocks"  # still fits the 64 bucket


def assert_warm_handoff(dg_cold: dict, dg_warm: dict) -> tuple[int, int]:
    """Counter-assert the incremental handoff: the warm frame shipped
    strictly fewer bytes than the cold one, some blocks were
    manifest-only (skipped), and some still shipped (the new tail)."""
    cold_bytes = dg_cold["handoff_bytes"]
    warm_bytes = dg_warm["handoff_bytes"] - cold_bytes
    assert 0 < warm_bytes < cold_bytes, \
        f"warm handoff not incremental: cold={cold_bytes} warm={warm_bytes}"
    blocks = dg_warm.get("blocks", 0) - dg_cold.get("blocks", 0)
    shipped = (dg_warm.get("blocks_shipped", 0)
               - dg_cold.get("blocks_shipped", 0))
    assert blocks > 0, f"warm handoff carried no block manifest: {dg_warm}"
    assert shipped < blocks, \
        f"warm handoff shipped every block ({shipped}/{blocks}) — " \
        f"the resident-prefix skip never engaged"
    return warm_bytes, cold_bytes


def assert_phase1_stats(stats: dict) -> dict:
    assert stats.get("role") == "decode", \
        f"decode host role wrong: {stats.get('role')}"
    # Decode tier books ADOPTION, not admission prefill: the prompt is
    # long enough for an aligned prefix, so zero admit dispatches.
    assert stats.get("admit_dispatches") == 0, \
        f"decode host inherited unified admission accounting: " \
        f"{stats.get('admit_dispatches')} admit dispatches"
    assert stats.get("adopt_dispatches", 0) >= 1, "no adoption dispatch"
    dg = stats.get("disagg") or {}
    assert dg.get("handoff_frames", 0) >= 1, f"no handoff counted: {dg}"
    assert dg.get("handoff_bytes", 0) > 0
    assert (dg.get("prefill_tier_s") or {}).get("count", 0) >= 1
    ph = dg.get("prefill_host") or {}
    assert ph.get("role") == "prefill", f"prefill host stats: {ph}"
    assert (ph.get("handoff") or {}).get("frames", 0) >= 1
    assert ph.get("handoffs", 0) >= 1  # scheduler-side counter
    # Prefill work lives HERE (this prompt spans > 1 chunk, so it lands
    # as chunk dispatches; short prompts would land as admit dispatches)
    assert (ph.get("admit_dispatches", 0)
            + ph.get("chunk_dispatches", 0)) >= 1
    return dg


async def run_backend_direct() -> int:
    """The two-host contract without the network layer (used when the
    `cryptography` dependency for the wire path is unavailable)."""
    from symmetry_tpu.provider.backends.base import (
        BackendRestartingError, InferenceRequest)
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager

    async def collect(backend, content):
        text = []
        async for chunk in backend.stream(InferenceRequest(
                messages=[{"role": "user", "content": content}],
                max_tokens=8, temperature=0.0)):
            if chunk.text:
                text.append(chunk.text)
        return "".join(text)

    backend = TpuNativeBackend(ConfigManager(
        config=provider_config_dict()))
    restarts_seen = []
    try:
        await backend.start()
        backend.on_host_restart = restarts_seen.append

        # phase 1: happy-path handoff
        text1 = await collect(backend, PROMPT)
        assert text1, "phase 1 streamed no text"
        dg = assert_phase1_stats(await backend.engine_stats())
        print(f"disagg smoke: phase 1 streamed {len(text1)} chars; "
              f"{dg['handoff_frames']} handoff frame(s), "
              f"{dg['handoff_bytes']} bytes, prefill-tier p50 "
              f"{(dg.get('prefill_tier_s') or {}).get('p50')}s")

        # phase 1b: block-manifest incremental handoff — the second
        # request extends PROMPT, its shared prefix blocks are already
        # resident on the decode tier, and the wire must carry only the
        # non-resident tail.
        text1b = await collect(backend, PROMPT_WARM)
        assert text1b, "phase 1b streamed no text"
        dg1b = (await backend.engine_stats()).get("disagg") or {}
        warm_bytes, cold_bytes = assert_warm_handoff(dg, dg1b)
        print(f"disagg smoke: phase 1b warm handoff shipped "
              f"{warm_bytes} bytes vs {cold_bytes} cold "
              f"({dg1b.get('blocks_shipped', 0) - dg.get('blocks_shipped', 0)}"
              f"/{dg1b.get('blocks', 0) - dg.get('blocks', 0)} blocks "
              f"on the wire)")

        # phase 2: prefill-host crash mid-request → restarting shed →
        # respawned pair serves the retry
        shed = False
        try:
            await collect(backend, PROMPT + " again?")
        except BackendRestartingError as exc:
            shed = True
            assert exc.retry_after_s is not None
        assert shed, "prefill crash did not shed as restarting"
        # The respawn (and its flight-recorder hook) runs async in the
        # supervisor — give it a beat before asserting on the hook.
        for _ in range(100):
            if restarts_seen:
                break
            await asyncio.sleep(0.1)
        assert restarts_seen == ["crash"], f"hook saw {restarts_seen}"
        text2 = None
        for _ in range(200):  # retry through the respawn window
            try:
                text2 = await collect(backend, PROMPT + " again?")
                break
            except BackendRestartingError:
                await asyncio.sleep(0.25)
        assert text2, "retry never completed on the respawned pair"
        stats2 = await backend.engine_stats()
        sup = stats2.get("supervisor") or {}
        assert sup.get("restarts", 0) >= 1, f"no restart recorded: {sup}"
        assert not sup.get("circuit_open"), "circuit breaker tripped"
        assert await backend.healthy()
        print(f"disagg smoke: phase 2 crash → restarting shed → retry "
              f"completed {len(text2)} chars on the respawned pair "
              f"(supervisor restarts={sup.get('restarts')})")
    finally:
        await backend.stop()
    return 0


async def run_network() -> int:
    """The full path: client → server → provider on the in-memory
    transport, recovery via client failover."""
    from symmetry_tpu.client.client import ChatRestart, SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.memory import MemoryTransport

    hub = MemoryTransport()
    server_ident = Identity.from_name("disagg-smoke-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://server")

    cfg_dict = provider_config_dict()
    cfg_dict["serverKey"] = server_ident.public_hex
    provider = SymmetryProvider(
        ConfigManager(config=cfg_dict), transport=hub,
        identity=Identity.from_name("disagg-smoke-p"),
        server_address="mem://server")
    await provider.start("mem://disagg-smoke-p")
    await provider.wait_registered()

    client = SymmetryClient(Identity.from_name("disagg-smoke-cli"), hub)

    # phase 1: happy-path handoff through the wire
    deltas = []
    async for item in client.chat_failover(
            "mem://server", server_ident.public_key, "tiny:disagg",
            [{"role": "user", "content": PROMPT}], max_tokens=8,
            temperature=0.0):
        deltas.append(item)
    assert not any(isinstance(d, ChatRestart) for d in deltas), \
        "phase 1 must not restart"
    text1 = "".join(d for d in deltas if isinstance(d, str))
    assert text1, "phase 1 streamed no text"
    dg = assert_phase1_stats(await provider.backend.engine_stats())
    print(f"disagg smoke: phase 1 streamed {len(text1)} chars over the "
          f"wire; {dg['handoff_frames']} handoff frame(s), "
          f"{dg['handoff_bytes']} bytes, prefill-tier p50 "
          f"{(dg.get('prefill_tier_s') or {}).get('p50')}s")

    # phase 1b: warm block-manifest handoff through the wire — shared
    # prefix blocks ride the manifest only, the tail ships.
    deltas1b = []
    async for item in client.chat_failover(
            "mem://server", server_ident.public_key, "tiny:disagg",
            [{"role": "user", "content": PROMPT_WARM}], max_tokens=8,
            temperature=0.0):
        deltas1b.append(item)
    text1b = "".join(d for d in deltas1b if isinstance(d, str))
    assert text1b, "phase 1b streamed no text"
    dg1b = (await provider.backend.engine_stats()).get("disagg") or {}
    warm_bytes, cold_bytes = assert_warm_handoff(dg, dg1b)
    print(f"disagg smoke: phase 1b warm handoff shipped {warm_bytes} "
          f"bytes vs {cold_bytes} cold")

    # phase 2: prefill-host crash mid-request → restarting shed →
    # client failover retry completes on the respawned pair
    restarts_seen = []
    provider.backend.on_host_restart = restarts_seen.append
    events = []
    async for item in client.chat_failover(
            "mem://server", server_ident.public_key, "tiny:disagg",
            [{"role": "user", "content": PROMPT + " again?"}],
            max_tokens=8, temperature=0.0, busy_retry_rounds=8):
        events.append(item)
    restarts = [e for e in events if isinstance(e, ChatRestart)]
    assert restarts, "prefill crash produced no failover restart"
    cut = events.index(restarts[-1])
    text2 = "".join(e for e in events[cut + 1:] if isinstance(e, str))
    assert text2, "no text after failover — request never completed"
    assert restarts_seen and restarts_seen[0] == "crash", \
        f"supervisor saw {restarts_seen}, expected a crash"

    for _ in range(100):  # let the supervisor bookkeeping settle
        if provider.backend._restarts >= 1 \
                and not provider.backend._restarting:
            break
        await asyncio.sleep(0.1)
    stats2 = await provider.backend.engine_stats()
    sup = stats2.get("supervisor") or {}
    assert sup.get("restarts", 0) >= 1, f"no restart recorded: {sup}"
    assert not sup.get("circuit_open"), "circuit breaker tripped"
    print(f"disagg smoke: phase 2 crash → restarting shed → "
          f"{len(restarts)} failover restart(s) → completed "
          f"{len(text2)} chars on the respawned pair "
          f"(supervisor restarts={sup.get('restarts')})")

    await provider.stop(drain_timeout_s=2)
    await server.stop()
    return 0


async def run_link_chaos() -> int:
    """Phase 3: the two tiers joined ONLY by the TCP handoff link, with
    a mid-handoff link drop injected via the disagg.net.drop_link seam."""
    from symmetry_tpu.provider.backends.base import (
        BackendRestartingError, InferenceRequest)
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.utils.faults import FAULTS

    cfg = provider_config_dict()
    cfg["name"] = "disagg-link-prov"
    # Network mode: inline PrefillNode over real TCP loopback; small
    # chunks so every handoff is genuinely multi-chunk on the wire; no
    # per-tier handoff-crash fault here (that was phases 1–2).
    cfg["tpu"]["disagg"] = {"peer": "tcp://127.0.0.1:0", "inline": True,
                            "chunk_kb": 4, "reconnect_base_s": 0.2}
    # The drop_link seam counts one hit per transfer attempt (fired
    # after the first chunk): request 1's handoff is hit 1 (clean),
    # request 2's handoff is hit 2 → the cable pull, mid-transfer.
    FAULTS.load({"disagg.net.drop_link": "drop_frame@nth=2"})

    async def collect(backend, content):
        text = []
        async for chunk in backend.stream(InferenceRequest(
                messages=[{"role": "user", "content": content}],
                max_tokens=8, temperature=0.0)):
            if chunk.text:
                text.append(chunk.text)
        return "".join(text)

    backend = TpuNativeBackend(ConfigManager(config=cfg))
    try:
        await backend.start()

        # happy path over the wire + the serialize-vs-wire split
        text1 = await collect(backend, PROMPT)
        assert text1, "link phase streamed no text"
        dg = assert_phase1_stats(await backend.engine_stats())
        assert dg.get("wire_frames", 0) >= 1, f"no wire split: {dg}"
        assert (dg.get("wire_s") or {}).get("count", 0) >= 1
        assert dg.get("wire_bytes", 0) > 0
        ho = ((dg.get("prefill_host") or {}).get("handoff") or {})
        assert ho.get("serialize_s", 0) > 0, \
            "serialize wall missing beside the wire split"
        link = dg.get("link") or {}
        assert link.get("connected") is True, f"link stats: {link}"
        node = dg.get("node") or {}
        assert node.get("handoffs_sent", 0) >= 1, f"node stats: {node}"
        print(f"disagg smoke: link phase streamed {len(text1)} chars "
              f"over TCP; wire p50 "
              f"{(dg.get('wire_s') or {}).get('p50')}s beside "
              f"serialize {ho.get('serialize_s')}s")

        # mid-handoff link drop → retryable shed → reconnect → retry
        shed = False
        try:
            await collect(backend, PROMPT + " once more")
        except BackendRestartingError:
            shed = True
        assert shed, "link drop did not shed the in-flight request"
        text2 = None
        for _ in range(200):  # retry through the reconnect window
            try:
                text2 = await collect(backend, PROMPT + " once more")
                break
            except BackendRestartingError:
                await asyncio.sleep(0.25)
        assert text2, "retry never completed on the re-dialed link"
        stats = await backend.engine_stats()
        dg = stats.get("disagg") or {}
        link = dg.get("link") or {}
        assert link.get("connects", 0) >= 2, f"no reconnect: {link}"
        assert link.get("drops", 0) >= 1, f"no drop recorded: {link}"
        assert link.get("partial_discards", 0) >= 1, \
            f"partial transfer not discarded: {link}"
        # ZERO partial adoptions: the decode host only ever saw intact,
        # CRC-verified frames (its adopt path booked no errors).
        ad = stats.get("adopt") or {}
        assert ad.get("errors", 0) == 0, f"decode host adopt stats: {ad}"
        sup = stats.get("supervisor") or {}
        assert sup.get("restarts", 0) == 0, \
            f"link loss must not restart the decode host: {sup}"
        print(f"disagg smoke: link phase drop → shed → reconnect "
              f"(connects={link.get('connects')}, "
              f"drops={link.get('drops')}, partial_discards="
              f"{link.get('partial_discards')}) → retry completed "
              f"{len(text2)} chars; zero partial adoptions")
    finally:
        await backend.stop()
        FAULTS.clear()
    return 0


async def run_pool_chaos() -> int:
    """Phase 4: the ELASTIC POOL churn contract. A 2×1 pool (two inline
    prefill nodes over the memory link, one decode host) takes sustained
    traffic; one prefill node is KILLED mid-traffic (crash — no drain,
    no leave). Every in-flight request must complete via the retryable
    shed + re-placement path on the survivor: zero non-retryable client
    outcomes, zero partial adoptions (decode adopt errors stay 0), zero
    decode-host restarts, and the pool metrics account the churn
    (member lost, re-placements counted)."""
    from symmetry_tpu.provider.backends.base import (
        BackendRestartingError, InferenceRequest)
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager

    cfg = provider_config_dict()
    cfg["name"] = "disagg-pool-prov"
    # Every prefill host's FIRST handoff stalls 2 s (delay seam on the
    # engine thread) — the deterministic window in which the node kill
    # lands with migrations genuinely in flight. No crash faults here.
    cfg["tpu"]["disagg"] = {
        "peer": "mem://pool-smoke", "reconnect_base_s": 0.2,
        "pool": {"prefill": 2, "decode": 1, "heartbeat_s": 1.0},
        "prefill": {"faults": {"disagg.handoff": "delay(2.0)@once"}},
    }

    async def collect(backend, content):
        text = []
        async for chunk in backend.stream(InferenceRequest(
                messages=[{"role": "user", "content": content}],
                max_tokens=8, temperature=0.0)):
            if chunk.text:
                text.append(chunk.text)
        return "".join(text)

    async def collect_retrying(backend, content):
        # The retryable shed is an ALLOWED outcome (client failover
        # retries through it); anything non-retryable fails the smoke.
        for _ in range(200):
            try:
                return await collect(backend, content)
            except BackendRestartingError:
                await asyncio.sleep(0.25)
        raise AssertionError(f"{content!r} never completed")

    backend = TpuNativeBackend(ConfigManager(config=cfg))
    try:
        await backend.start()
        tasks = [asyncio.ensure_future(
            collect_retrying(backend, f"{PROMPT} #{i}"))
            for i in range(4)]
        await asyncio.sleep(0.7)  # placements made; handoffs mid-delay
        pending_before = backend._broker.pending
        await backend._inline_nodes[0].kill()  # node death mid-traffic
        texts = await asyncio.gather(*tasks)
        assert all(texts), f"incomplete streams: {[len(t) for t in texts]}"
        stats = await backend.engine_stats()
        pool = (stats.get("disagg") or {}).get("pool") or {}
        members = pool.get("members") or {}
        assert members.get("prefill-0", {}).get("state") == "lost", members
        assert members.get("prefill-1", {}).get("state") == "healthy", \
            members
        assert pool.get("losses", 0) >= 1, pool
        assert pool.get("re_placements", 0) >= 1, \
            f"no re-placement counted (pending at kill: " \
            f"{pending_before}): {pool}"
        sup = stats.get("supervisor") or {}
        assert sup.get("restarts", 0) == 0, \
            f"node death must not restart a decode host: {sup}"
        ad = stats.get("adopt") or {}
        assert ad.get("errors", 0) == 0, \
            f"partial/garbage adoption on the decode host: {ad}"
        print(f"disagg smoke: pool phase — killed prefill-0 of 2×1 "
              f"under load ({pending_before} migrations in flight); "
              f"all 4 requests completed, re_placements="
              f"{pool.get('re_placements')}, losses="
              f"{pool.get('losses')}, decode restarts 0, adopt errors 0")
    finally:
        await backend.stop()
    return 0


async def run_pool_affinity() -> int:
    """Phase 5: cache-affine session routing across a 2×2 pool. A
    session's turn 1 lands cold somewhere; its gossiped radix summary
    then makes turn 2 (same conversation, resubmitted full prefix)
    affinity-route back to the member holding the cache (counter
    asserted), and the per-member shipped-block ledger makes the warm
    handoff ship fewer bytes than the cold one. Killing the warm member
    must drop it to a clean cold re-place on the survivor — never an
    error, never a stale-ledger skip against the respawn's empty cache."""
    from symmetry_tpu.provider.backends.base import (
        BackendRestartingError, InferenceRequest)
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager

    cfg = provider_config_dict()
    cfg["name"] = "disagg-affinity-prov"
    # 2×2 pool, fast heartbeat so summaries gossip between turns; the
    # engine-side summary cache refreshes faster than the heartbeat
    # asks. No fault seams in this phase.
    cfg["tpu"]["disagg"] = {
        "peer": "mem://pool-affinity", "reconnect_base_s": 0.2,
        "pool": {"prefill": 2, "decode": 2, "heartbeat_s": 0.3},
    }
    cfg["tpu"]["prefix_gossip_s"] = 0.1

    async def collect(backend, content):
        text = []
        async for chunk in backend.stream(InferenceRequest(
                messages=[{"role": "user", "content": content}],
                max_tokens=8, temperature=0.0)):
            if chunk.text:
                text.append(chunk.text)
        return "".join(text)

    async def collect_retrying(backend, content):
        for _ in range(200):
            try:
                return await collect(backend, content)
            except BackendRestartingError:
                await asyncio.sleep(0.25)
        raise AssertionError(f"{content!r} never completed")

    async def pool_stats(backend):
        stats = await backend.engine_stats()
        return stats, (stats.get("disagg") or {}).get("pool") or {}

    backend = TpuNativeBackend(ConfigManager(config=cfg))
    try:
        await backend.start()

        # turn 1: cold — no summaries gossiped yet, so the placement
        # books a non-hit outcome and ships the full frame.
        text1 = await collect(backend, PROMPT)
        assert text1, "affinity phase turn 1 streamed no text"
        stats, pool = await pool_stats(backend)
        assert pool.get("affinity_hit", 0) == 0, pool
        assert (pool.get("affinity_cold", 0)
                + pool.get("affinity_load_only", 0)) >= 1, pool
        dg1 = stats.get("disagg") or {}
        per1 = (dg1.get("per_member") or {})

        # let the gossip land: a few heartbeats carry the prefill
        # members' radix summaries (and the decode members') back to
        # the router.
        for _ in range(40):
            _, pool = await pool_stats(backend)
            if any((m.get("summary_digests") or 0) > 0
                   for m in (pool.get("members") or {}).values()):
                break
            await asyncio.sleep(0.15)
        members = pool.get("members") or {}
        assert any((m.get("summary_digests") or 0) > 0
                   for m in members.values()), \
            f"no radix summary ever gossiped: {members}"

        # turn 2: the same conversation grown by one exchange — the
        # shared prefix must pull it back to the warm member.
        text2 = await collect(backend, PROMPT + " and why it helps")
        assert text2, "affinity phase turn 2 streamed no text"
        stats, pool = await pool_stats(backend)
        assert pool.get("affinity_hit", 0) >= 1, \
            f"turn 2 was not affinity-routed: {pool}"
        members = pool.get("members") or {}
        warm = [mid for mid, m in members.items()
                if m.get("tier") == "prefill" and m.get("hit_blocks", 0) > 0]
        assert warm, f"no prefill member banked predicted hits: {members}"
        # per-member ledger: the decode member the warm handoff reached
        # shipped fewer blocks than the frame covers (the cold turn
        # shipped everything).
        dg2 = stats.get("disagg") or {}
        per2 = dg2.get("per_member") or {}
        warm_members = [
            mid for mid, led in per2.items()
            if (led.get("warm_frames", 0)
                > (per1.get(mid) or {}).get("warm_frames", 0))]
        assert warm_members, \
            f"no per-member warm handoff: before={per1} after={per2}"

        # kill the warm prefill member: the session must drop to a cold
        # re-place on the survivor — completed stream, no adopt errors,
        # and the loss accounted.
        warm_idx = int(warm[0].rsplit("-", 1)[1])
        await backend._inline_nodes[warm_idx].kill()
        # Same session prompt re-asked: its warm member is gone, so the
        # digests match nothing placeable — a cold re-place, not a
        # stale-affinity pull toward the corpse.
        text3 = await collect_retrying(backend,
                                       PROMPT + " and why it helps")
        assert text3, "post-kill turn streamed no text"
        stats, pool = await pool_stats(backend)
        members = pool.get("members") or {}
        assert members.get(warm[0], {}).get("state") == "lost", members
        assert pool.get("losses", 0) >= 1, pool
        ad = stats.get("adopt") or {}
        assert ad.get("errors", 0) == 0, \
            f"stale ledger/summary corrupted adoption: {ad}"
        print(f"disagg smoke: affinity phase — turn 2 affinity-routed "
              f"(hit placements={pool.get('affinity_hit')}, predicted "
              f"blocks on {warm[0]}={members.get(warm[0], {}).get('hit_blocks')}), "
              f"warm handoff ledger {warm_members} shipped partial "
              f"frames; killed {warm[0]} → cold re-place completed "
              f"{len(text3)} chars with zero adopt errors")
    finally:
        await backend.stop()
    return 0


def main() -> int:
    try:
        import cryptography  # noqa: F401 — wire-path dependency probe

        runner = run_network()
    except ImportError:
        print("disagg smoke: cryptography unavailable — running the "
              "backend-direct mode (same two-host contracts, no wire)",
              file=sys.stderr)
        runner = run_backend_direct()
    loop = asyncio.new_event_loop()
    try:
        rc = loop.run_until_complete(asyncio.wait_for(runner, 900))
        if rc == 0:
            rc = loop.run_until_complete(
                asyncio.wait_for(run_link_chaos(), 900))
        if rc == 0:
            rc = loop.run_until_complete(
                asyncio.wait_for(run_pool_chaos(), 900))
        if rc == 0:
            rc = loop.run_until_complete(
                asyncio.wait_for(run_pool_affinity(), 900))
        return rc
    except AssertionError as exc:
        print(f"disagg smoke FAILED: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
