#!/usr/bin/env python3
"""CI smoke for the symprof layer + benchdiff (no TPU, no network).

Phase 1 — device-time attribution on a live scheduler: a tiny inproc
engine with `tpu.profile_sample` = 1 serves real scheduler traffic
(plain prompts + a chunked long prompt); the devprof stats block must
carry per-kind device-duration p50s and a dispatch-gap share, and the
merged Perfetto export must contain a `device` process track with at
least one slice per probed kind and no negative timestamps. The export
is written to --out and uploaded as a workflow artifact.

Phase 2 — benchdiff verdicts on a REAL capture: one `bench.py --smoke
--profile-sample 1` run produces a stamped capture (asserting the
bench-side devprof block on the way); benchdiff must exit 0 against an
equal copy (markdown table emitted), 1 against a tampered-regression
copy, and 2 against a fingerprint-mismatched copy.

Phase 3 — the on-demand jax.profiler capture: one bounded
capture_device_profile window must produce a non-empty trace directory
and the single-flight guard must refuse a concurrent capture.

Run: python tools/profiling_smoke.py [--out profiling_smoke_perfetto.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(f"[profiling_smoke] {msg}", flush=True)


def phase1_device_track(out_path: str) -> None:
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset
    from symmetry_tpu.utils.trace import export_perfetto

    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    engine = InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=96,
        prefill_buckets=(16, 48), cache_dtype=jnp.float32,
        decode_block=2, prefill_chunk=16, profile_sample=1)
    engine.warmup()
    sched = Scheduler(engine, debug_invariants=True)

    results: dict[int, list] = {0: [], 1: [], 2: []}
    done = {i: threading.Event() for i in results}
    prompts = [list(b"hello symprof"), list(b"second stream"),
               # > prefill_chunk: drives the chunked-prefill path so the
               # `chunk` dispatch kind gets probed too.
               list(b"a long prompt that needs chunked prefill here..")]
    for i, ids in enumerate(prompts):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=ids, sampling=SamplingParams(),
                                max_new_tokens=12, emit=emit, id=f"r{i}"))
    sched.start()
    for ev in done.values():
        assert ev.wait(180), "request did not complete"
    sched.stop()

    stats = sched.stats()
    dev = stats.get("devprof")
    assert dev, "scheduler stats carry no devprof block"
    probes = dev.get("probes") or {}
    for kind in ("prefill", "chunk", "decode_block"):
        assert probes.get(kind, 0) >= 1, \
            f"no completion probe fired for kind {kind!r}: {probes}"
        p50 = (dev["device_s"].get(kind) or {}).get("p50")
        assert p50 is not None and p50 >= 0, \
            f"kind {kind!r} has no device-duration p50"
    gap = dev.get("dispatch_gap_s") or {}
    assert gap.get("count", 0) >= 1, "no dispatch-gap samples"
    assert dev.get("gap_share") is not None, "no gap_share"
    assert 0.0 <= dev["gap_share"] <= 1.0, dev["gap_share"]
    log(f"devprof: probes={probes} gap_share={dev['gap_share']} "
        f"gap_p50={gap.get('p50')}")

    # The merged export: scheduler spans + the device track, exactly the
    # components the host's `trace` op ships in process mode.
    perfetto = export_perfetto([sched.trace_export(),
                                engine.devprof.component("device")])
    events = perfetto["traceEvents"]
    pids = {e["args"]["name"]: e["pid"] for e in events
            if e.get("name") == "process_name"}
    assert "device" in pids, f"no device process track: {sorted(pids)}"
    dev_pid = pids["device"]
    dev_slices = [e for e in events
                  if e.get("ph") == "X" and e.get("pid") == dev_pid]
    kinds = {e["name"] for e in dev_slices}
    for kind in ("prefill", "chunk", "decode_block", "dispatch_gap"):
        assert kind in kinds, \
            f"device track missing a {kind!r} slice: {sorted(kinds)}"
    for e in events:
        if e.get("ph") in ("X", "C"):
            assert e["ts"] >= 0, f"negative timestamp: {e}"
            assert e.get("dur", 0) >= 0, f"negative duration: {e}"
    with open(out_path, "w") as fh:
        json.dump(perfetto, fh)
    log(f"phase 1 OK: device track with {len(dev_slices)} slices "
        f"({sorted(kinds)}) → {out_path}")


def phase2_benchdiff() -> None:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--profile-sample", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode in (0, None) and out.stdout.strip(), \
        f"bench --smoke failed rc={out.returncode}:\n{out.stderr[-2000:]}"
    capture = json.loads(out.stdout.strip().splitlines()[-1])
    # The acceptance contract: a profile_sample'd bench reports per-kind
    # device p50s and a dispatch-gap share in its JSON, stamped.
    assert capture.get("schema") == 1, capture.get("schema")
    assert capture.get("config_fingerprint"), "capture is unstamped"
    assert capture.get("config", {}).get("mode") == "smoke"
    dev = capture.get("devprof") or {}
    p50s = dev.get("device_p50_ms") or {}
    assert p50s.get("prefill") is not None, p50s
    assert p50s.get("decode_block") is not None, p50s
    assert dev.get("gap_share") is not None, dev
    log(f"bench --smoke devprof: p50s={p50s} gap_share={dev['gap_share']}")

    tmp = tempfile.mkdtemp(prefix="benchdiff_smoke_")
    base = os.path.join(tmp, "base.json")
    with open(base, "w") as fh:
        json.dump(capture, fh)

    def run_diff(cand_obj: dict, *args: str) -> tuple[int, str]:
        cand = os.path.join(tmp, "cand.json")
        with open(cand, "w") as fh:
            json.dump(cand_obj, fh)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "benchdiff.py"),
             base, cand, *args],
            capture_output=True, text=True, timeout=120)
        return proc.returncode, proc.stdout + proc.stderr

    # Equal capture → exit 0, with a markdown table.
    rc, text = run_diff(capture)
    assert rc == 0, f"equal-capture diff exited {rc}:\n{text}"
    assert "| metric |" in text and "REGRESSED" not in text, text

    # Tampered headline (half the tok/s) → exit 1, REGRESSED named.
    worse = json.loads(json.dumps(capture))
    worse["value"] = round(capture["value"] * 0.5, 1)
    rc, text = run_diff(worse)
    assert rc == 1, f"regressed diff exited {rc}:\n{text}"
    assert "REGRESSED" in text, text

    # Different config fingerprint → refused loudly, exit 2.
    other = json.loads(json.dumps(capture))
    other["config"] = {**other["config"], "slots": 99}
    other["config_fingerprint"] = "feedfacefeedface"
    rc, text = run_diff(other)
    assert rc == 2, f"cross-config diff exited {rc} (want refusal):\n{text}"
    assert "REFUSING" in text and "slots" in text, text
    # ... unless forced (the deliberate knob-A/B path).
    rc, text = run_diff(other, "--force")
    assert rc in (0, 1), f"forced diff exited {rc}:\n{text}"
    log("phase 2 OK: benchdiff exit codes 0/1/2 + markdown table")


def phase3_capture() -> None:
    from symmetry_tpu.utils.devprof import capture_device_profile

    import jax
    import jax.numpy as jnp

    # A little device work for the window to observe.
    def burn():
        x = jnp.ones((64, 64))
        for _ in range(20):
            x = x @ x / 64.0
        jax.block_until_ready(x)

    tmp = tempfile.mkdtemp(prefix="profiling_smoke_jaxprof_")
    t = threading.Thread(target=burn)
    t.start()
    path = capture_device_profile(tmp, duration_s=0.3)
    t.join()
    assert os.path.isdir(path), path
    contents = [os.path.join(dp, f) for dp, _dn, fn in os.walk(path)
                for f in fn]
    assert contents, f"capture produced an empty trace dir: {path}"
    # Single-flight guard: a concurrent capture must refuse, not queue.
    hold = threading.Thread(
        target=capture_device_profile, args=(tmp,), kwargs={"duration_s": 1.0})
    hold.start()
    time.sleep(0.2)
    try:
        capture_device_profile(tmp, duration_s=0.1)
        raise AssertionError("concurrent capture was not refused")
    except RuntimeError:
        pass
    finally:
        hold.join()
    log(f"phase 3 OK: jax.profiler capture → {path} "
        f"({len(contents)} artifact file(s)); concurrent capture refused")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="profiling_smoke_perfetto.json")
    args = ap.parse_args()
    t0 = time.monotonic()
    phase1_device_track(args.out)
    phase2_benchdiff()
    phase3_capture()
    log(f"ALL PHASES OK in {time.monotonic() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
