"""CI trace smoke: one echo-backend request, end to end, with tracing on.

Runs the full client → server → provider path on the in-memory transport
(no TPU, no subprocess), chats once with a known trace id, pulls the
merged trace through the provider `trace` op, and validates the Perfetto
export the way a reviewer would load it:

  - parses as Chrome trace-event JSON (traceEvents list, well-formed
    "X"/"C"/"M" events);
  - spans from >= 3 distinct components (client, provider, echo backend);
  - the chat's trace id appears in >= 3 components' spans (propagation,
    not just co-residence);
  - every event timestamp is non-negative (one reconciled clock, no
    negative spans).

Exit 0 and write the JSON to --out on success; exit 1 with a reason
otherwise. The CI workflow uploads the JSON as an artifact.

Run: python tools/trace_smoke.py --out trace_smoke_perfetto.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


async def run(out_path: str) -> int:
    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.memory import MemoryTransport
    from symmetry_tpu.utils.trace import new_trace_id

    hub = MemoryTransport()
    server_ident = Identity.from_name("trace-smoke-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://server")

    cfg = ConfigManager(config={
        "name": "trace-smoke-prov",
        "public": True,
        "serverKey": server_ident.public_hex,
        "modelName": "echo:smoke",
        "apiProvider": "echo",
        "dataCollectionEnabled": False,
        "flightRecorder": {"enabled": False},
    })
    provider = SymmetryProvider(
        cfg, transport=hub, identity=Identity.from_name("trace-smoke-prov"),
        server_address="mem://server")
    await provider.start("mem://trace-smoke-prov")
    await provider.wait_registered()

    client = SymmetryClient(Identity.from_name("trace-smoke-cli"), hub)
    details = await client.request_provider(
        "mem://server", server_ident.public_key, "echo:smoke")
    session = await client.connect(details)
    trace_id = new_trace_id()
    try:
        text = "".join([d async for d in session.chat(
            [{"role": "user", "content": "hello observable world"}],
            trace_id=trace_id)])
        assert text == "hello observable world", f"echo mismatch: {text!r}"
        perfetto = await client.export_trace(session)
    finally:
        await session.close()
        await provider.stop()
        await server.stop()

    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(perfetto, fh)

    # ---- validation ----------------------------------------------------
    events = perfetto.get("traceEvents")
    assert isinstance(events, list) and events, "no traceEvents"
    comp_by_pid: dict[int, str] = {}
    for ev in events:
        assert isinstance(ev, dict), f"non-dict event: {ev!r}"
        assert ev.get("ph") in ("X", "C", "M"), f"bad phase: {ev!r}"
        if ev["ph"] == "M" and ev.get("name") == "process_name":
            comp_by_pid[ev["pid"]] = ev["args"]["name"]
        if ev["ph"] in ("X", "C"):
            assert isinstance(ev.get("ts"), (int, float)), f"no ts: {ev!r}"
            assert ev["ts"] >= 0, f"negative ts (unreconciled clock): {ev!r}"
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), (int, float)), f"no dur: {ev!r}"
            assert isinstance(ev.get("name"), str) and ev["name"]

    span_comps = {comp_by_pid[e["pid"]] for e in events if e["ph"] == "X"}
    traced_comps = {comp_by_pid[e["pid"]] for e in events
                    if e["ph"] == "X"
                    and e.get("args", {}).get("trace_id") == trace_id}
    print(f"trace smoke: {len(events)} events; spans from {sorted(span_comps)}; "
          f"trace_id {trace_id} seen in {sorted(traced_comps)}")
    assert len(span_comps) >= 3, \
        f"need spans from >= 3 components, got {sorted(span_comps)}"
    assert len(traced_comps) >= 3, \
        f"trace id propagated to only {sorted(traced_comps)}"
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="trace_smoke_perfetto.json")
    args = ap.parse_args()
    try:
        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run(args.out), 120))
    except AssertionError as exc:
        print(f"trace smoke FAILED: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
