"""Coalesced-prefill batch sweep at the bench point (round-3 verdict #2).

Builds the north-star engine config (llama3-8b int8+kv8, 128 slots @ 640
ctx, 128-token bucket) and times one prefill+insert dispatch at every
allowed batch width, plus the compile cost of each (batch, bucket) grid
point. The output answers: how many dispatches does a 128-prompt burst
need, and what does each cost?

Run on the real chip:  python tools/sweep_prefill.py
Smoke (CPU, tiny):     python tools/sweep_prefill.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=128)
    ap.add_argument("--max-seq", type=int, default=640)
    ap.add_argument("--bucket", type=int, default=128)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")
        args.preset, args.slots, args.max_seq, args.bucket = "tiny", 8, 64, 16

    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset
    from symmetry_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    quant = not args.smoke
    config = preset(args.preset)
    dtype = jnp.float32 if args.smoke else jnp.bfloat16
    t0 = time.perf_counter()
    params = init_params(config, jax.random.key(0), dtype, quantize=quant)
    print(f"param init: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    engine = InferenceEngine(
        config, params, ByteTokenizer(), max_slots=args.slots,
        max_seq_len=args.max_seq, prefill_buckets=(args.bucket,),
        cache_dtype=dtype, decode_block=16, kv_quant=quant)

    prompt = [p % 200 for p in range(1, args.bucket - 8)]
    rows = []
    for batch in engine.prefill_batches_for(args.bucket):
        if batch > args.slots:
            continue
        # First call compiles (prefill + insert for this batch width).
        t0 = time.perf_counter()
        engine.prefill_and_insert_many(
            [(s, prompt, SamplingParams(temperature=0.7, seed=s))
             for s in range(batch)])
        compile_s = time.perf_counter() - t0
        times = []
        for r in range(args.repeats):
            t0 = time.perf_counter()
            engine.prefill_and_insert_many(
                [(s, prompt, SamplingParams(temperature=0.7, seed=s))
                 for s in range(batch)])
            times.append(time.perf_counter() - t0)
        best = min(times)
        rows.append({
            "batch": batch,
            "dispatch_s": round(best, 3),
            "per_prompt_s": round(best / batch, 4),
            "compile_s": round(compile_s, 1),
            "dispatches_for_128": -(-128 // batch),
            "ramp_s_for_128": round(best * (-(-128 // batch)), 1),
        })
        print(json.dumps(rows[-1]), file=sys.stderr)

    print(json.dumps({"preset": args.preset, "bucket": args.bucket,
                      "slots": args.slots, "sweep": rows}))


if __name__ == "__main__":
    main()
