#!/usr/bin/env python3
"""symlint — the project-invariant static-analysis gate.

Runs the eight checkers in symmetry_tpu/analysis/ over the repo — six
flat AST passes plus the two path-sensitive dataflow checkers
(lifecycle, donation) — and exits non-zero when any finding is not
covered by the baseline file, so CI fails on protocol/concurrency/
recompile/fault-seam/lifecycle drift before the test suite even starts
(the whole run is ~6 s of `ast.parse` + checker passes, no JAX import,
no device; CI asserts the 10 s budget).

Usage:
    python tools/symlint.py                  # text output, repo root
    python tools/symlint.py --json           # machine-readable report
    python tools/symlint.py --sarif out.sarif  # + SARIF 2.1.0 file (CI
                                             # uploads it so findings
                                             # annotate the PR diff)
    python tools/symlint.py --checker wire-contract --checker fault-seam
    python tools/symlint.py --baseline tools/symlint_baseline.json
    python tools/symlint.py --no-baseline    # show EVERYTHING
    python tools/symlint.py path/a.py        # report only these files

Positional paths FILTER the report, they do not shrink the scan: the
checkers are cross-file by design (a producer's consumer usually lives
in another file), so the whole repo is always analyzed and findings
are then restricted to the named files. Unused-baseline reporting is
suppressed in filtered mode — entries for unlisted files are not
stale.

Baseline workflow: a finding that is intentional (e.g. a per-request
dict key owned by one thread at a time) gets a justified entry in
tools/symlint_baseline.json keyed by its line-number-free fingerprint
(printed with --json, or with --fingerprints in text mode). Unused
baseline entries are reported so stale suppressions cannot silently
shadow a future regression; --strict-baseline turns them into a
failure.

Exit codes: 0 clean (or baseline-only), 1 new findings (or unused
baseline entries under --strict-baseline), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from symmetry_tpu.analysis import ALL_CHECKERS, Baseline, run  # noqa: E402
from symmetry_tpu.analysis.core import iter_py_files  # noqa: E402

DEFAULT_BASELINE = os.path.join("tools", "symlint_baseline.json")
SCHEMA_VERSION = 1
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"
SARIF_VERSION = "2.1.0"


def sarif_report(findings, checkers, baseline) -> dict:
    """SARIF 2.1.0 document for `findings`. One rule per finding code
    (its checker's doc as the description); baselined findings carry a
    `suppressions` entry quoting the baseline justification, which
    GitHub code scanning honors (they upload but do not alert), so the
    inline PR annotations show exactly the NEW findings."""
    rules = [{
        "id": code,
        "name": f"{spec.name}/{code}",
        "shortDescription": {"text": f"[{spec.name}] {spec.doc}"},
    } for spec in checkers for code in spec.codes]
    reasons = {}
    if baseline is not None:
        reasons = {e["fingerprint"]: e.get("reason", "")
                   for e in baseline.entries if isinstance(e, dict)}
    results = []
    for f in findings:
        r = {
            "ruleId": f.code,
            "level": "note" if f.baselined else "error",
            "message": {"text": f"[{f.checker}] {f.message}"},
            "partialFingerprints": {
                "symlintFingerprint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "%SRCROOT%"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if f.baselined:
            r["suppressions"] = [{
                "kind": "external",
                "justification": reasons.get(f.fingerprint, ""),
            }]
        results.append(r)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "symlint",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="symlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="repo-relative .py files to REPORT on (the "
                         "scan always covers the whole repo — the "
                         "checkers are cross-file)")
    ap.add_argument("--root", default=_REPO_ROOT,
                    help="repo root to scan (default: this checkout)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write a SARIF 2.1.0 report to PATH "
                         "(github/codeql-action/upload-sarif annotates "
                         "PR diffs with it; baselined findings upload "
                         "as suppressed notes)")
    ap.add_argument("--checker", action="append", default=None,
                    metavar="NAME",
                    help="run only this checker (repeatable); "
                         "see --list-checkers")
    ap.add_argument("--list-checkers", action="store_true",
                    help="list checker names and exit")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"suppression file (default: {DEFAULT_BASELINE} "
                         f"under --root when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="fail when baseline entries matched nothing")
    ap.add_argument("--fingerprints", action="store_true",
                    help="append each finding's fingerprint in text mode "
                         "(what a baseline entry must quote)")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for spec in ALL_CHECKERS:
            print(f"{spec.name:18s} {', '.join(spec.codes):38s} {spec.doc}")
        return 0

    checkers = ALL_CHECKERS
    if args.checker:
        by_name = {s.name: s for s in ALL_CHECKERS}
        unknown = [c for c in args.checker if c not in by_name]
        if unknown:
            print(f"symlint: unknown checker(s): {', '.join(unknown)} "
                  f"(have: {', '.join(by_name)})", file=sys.stderr)
            return 2
        checkers = tuple(by_name[c] for c in args.checker)

    baseline = None
    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or os.path.join(args.root,
                                                      DEFAULT_BASELINE)
        if os.path.exists(baseline_path):
            try:
                baseline = Baseline.load(baseline_path)
            except (ValueError, OSError, json.JSONDecodeError) as exc:
                print(f"symlint: bad baseline {baseline_path}: {exc}",
                      file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"symlint: baseline not found: {baseline_path}",
                  file=sys.stderr)
            return 2

    # Filter paths are root-relative; absolute paths are re-anchored.
    only = {(os.path.relpath(p, args.root) if os.path.isabs(p) else p)
            .replace(os.sep, "/") for p in args.paths}
    try:
        findings = run(args.root, checkers, baseline)
    except (OSError, ValueError) as exc:
        print(f"symlint: {exc}", file=sys.stderr)
        return 2
    if only:
        # A filter entry that matches nothing scanned is a broken
        # invocation (typo, moved file), not a clean result — a hook
        # that silently checks nothing is worse than no hook.
        scanned = set(iter_py_files(args.root))
        ghosts = sorted(p for p in only if p not in scanned)
        if ghosts:
            print(f"symlint: path filter matched no scanned file: "
                  f"{', '.join(ghosts)}", file=sys.stderr)
            return 2
        findings = [f for f in findings if f.path in only]

    new = [f for f in findings if not f.baselined]
    old = [f for f in findings if f.baselined]
    # Staleness is only judgeable for what actually ran: in path-
    # filtered mode skip the unused report entirely, and under a
    # --checker filter only consider entries whose code belongs to a
    # selected checker — a C202 suppression is not stale just because
    # this run was wire-contract-only.
    unused: list[str] = []
    if baseline is not None and not only:
        selected_codes = {c for s in checkers for c in s.codes}
        unused = [fp for fp in baseline.unused()
                  if fp.split(":", 1)[0] in selected_codes]

    if args.sarif:
        # Written BEFORE the exit-code decision: a failing run is
        # exactly when CI needs the file to annotate the diff.
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(sarif_report(findings, checkers, baseline), fh,
                      indent=2)
            fh.write("\n")

    if args.as_json:
        report = {
            "version": SCHEMA_VERSION,
            "root": args.root,
            "checkers": [s.name for s in checkers],
            "baseline": baseline_path if baseline is not None else None,
            "findings": [f.to_dict() for f in findings],
            "baseline_unused": unused,
            "counts": {"total": len(findings), "new": len(new),
                       "baselined": len(old)},
        }
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            print(f.render())
            if args.fingerprints:
                print(f"    fingerprint: {f.fingerprint}")
        for fp in unused:
            print(f"symlint: baseline entry matched nothing "
                  f"(stale? prune it): {fp}", file=sys.stderr)
        summary = (f"symlint: {len(new)} new finding(s), "
                   f"{len(old)} baselined, "
                   f"{len(checkers)} checker(s)")
        print(summary, file=sys.stderr)

    if new:
        return 1
    if unused and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
