"""Multichip fused-dequant smoke: token identity pinned three ways.

Boots three tiny engines on a virtual 8-device CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8, self-pinned in a
subprocess so the ambient backend doesn't matter):

  1. fused TP=2   — packed tile layout sharded over the model axis,
                    shard_map'd Pallas kernel (interpret mode on CPU)
  2. unfused TP=2 — same mesh, same shardings, XLA mixed dot
  3. fused TP=1   — single device, the pre-mesh packed path

and asserts, over a greedy prompt + 10 decode steps:

  * token identity across all three builds — the sharded fused kernel
    changes the schedule, never the numbers (psum-then-scale matches
    the mixed dot's reduce order, see ops/qmm.py w8a16_apply_sharded);
  * zero steady-state recompiles on every build: compile_cache_sizes()
    taken after warmup must equal the counts after real traffic — the
    engine warmup's dispatch-cache closure pass covers the serving
    signature classes (engine.py warmup).

CI runs this on every push (ci.yml "Multichip fused smoke").
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SNIPPET = r"""
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_default_matmul_precision", "highest")
from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, param_logical_axes, preset
from symmetry_tpu.models.llama import quantize_params
from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

assert jax.device_count() == 8, jax.device_count()

def run(fused, tp):
    cfg = preset("tiny-mha")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    mesh = None
    if tp > 1:
        mesh = build_mesh(MeshSpec(data=1, model=tp))
        params = jax.device_put(
            params, shardings_for(param_logical_axes(cfg), mesh))
    params = quantize_params(params)
    eng = InferenceEngine(cfg, params, ByteTokenizer(), mesh=mesh,
                          max_slots=2, max_seq_len=64,
                          prefill_buckets=(16,), cache_dtype=jnp.float32,
                          fused_dequant=fused)
    eng.warmup()
    warm = eng.compile_cache_sizes()
    toks = [eng.prefill_and_insert(0, list(b"mesh parity"),
                                   SamplingParams())]
    for _ in range(10):
        toks.append(int(eng.decode_steps()[0][0]))
    # a second admission + decode wave, so the steady-state check sees
    # both burst and in-flight signature classes
    eng.prefill_and_insert(1, list(b"second"), SamplingParams())
    for _ in range(3):
        eng.decode_steps()
    served = eng.compile_cache_sizes()
    assert served == warm, (
        f"steady-state recompile (fused={fused}, tp={tp}): "
        f"{warm} -> {served}")
    return toks

tp2_fused = run(True, 2)
tp2_unfused = run(False, 2)
single_fused = run(True, 1)
assert tp2_fused == tp2_unfused, (tp2_fused, tp2_unfused)
assert tp2_fused == single_fused, (tp2_fused, single_fused)
print("MULTICHIP_FUSED_OK toks=%s" % (tp2_fused,))
"""


def main() -> int:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
           and not k.startswith("TPU")
           and not k.startswith("PJRT")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run([sys.executable, "-c", _SNIPPET], env=env,
                          cwd=REPO, text=True, capture_output=True,
                          timeout=900)
    sys.stdout.write(proc.stdout)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-3000:])
        print("[multichip_fused_smoke] FAILED", file=sys.stderr)
        return 1
    assert "MULTICHIP_FUSED_OK" in proc.stdout
    print("[multichip_fused_smoke] three-way token identity + zero "
          "steady-state recompiles: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
