"""Does the ragged decode-attention kernel win at 640 capacity under
PARTIAL occupancy (the serving regime), not just full (round-3's gate
measurement)? Times the trunk at several occupancies, kernel vs einsum."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time
import jax, jax.numpy as jnp
from _bench_util import sync
from symmetry_tpu.models import llama
from symmetry_tpu.ops import decode_attention as da

cfg = llama.preset("llama3-8b")
B, T = 128, 640
params = llama.init_params(cfg, jax.random.key(0), jnp.bfloat16, quantize=True)

def time_at(occ, use_kernel, n=15):
    real = da.supports
    da.supports = (lambda *a, **k: True) if use_kernel else (lambda *a, **k: False)
    try:
        cache = llama.init_cache(cfg, B, T, jnp.bfloat16, quantized=True)
        cache = cache._replace(lengths=jnp.full((B,), occ, jnp.int32))
        tok = jnp.ones((B, 1), jnp.int32)
        trunk = jax.jit(lambda p, t, c: llama.forward_hidden(p, cfg, t, c),
                        donate_argnums=(2,))
        for _ in range(3):
            h, cache = trunk(params, tok, cache)
        sync(h)
        t0 = time.perf_counter()
        for _ in range(n):
            h, cache = trunk(params, tok, cache)
        sync(h)
        return (time.perf_counter() - t0) / n * 1e3
    finally:
        da.supports = real

for occ in (128, 320, 512, 620):
    ein = time_at(occ, False)
    ker = time_at(occ, True)
    print(f"occ {occ:4d}/640: einsum {ein:6.2f} ms  kernel {ker:6.2f} ms  "
          f"({ein - ker:+.2f})", flush=True)
