"""70B int8 fit plan: per-device byte table + reduced-geometry dryrun.

Two halves, matching the round-19 acceptance row (BASELINE.md):

1. `--table`: jax.eval_shape the llama3-70b int8 param tree and the KV
   cache under a (dcn_data x ici_model) mesh and the default megatron
   rules, and fold each abstract leaf down to PER-DEVICE bytes. No
   weight is ever materialized — the table is pure shape arithmetic, so
   it runs in milliseconds on any host and answers "does 70B int8 fit
   a v5e-16 (2 hosts x 8 chips, 16 GB HBM each)?" before anyone rents
   the slice. Each int8 matmul leaf also gets a packability verdict at
   the given TP degree (TPU tile floors against the PER-SHARD dims), so
   the table doubles as the fused-dequant coverage plan: which leaves
   ride the packed kernel and which degrade to the mixed dot.

2. `--dryrun`: boot the REAL fused engine at 70B geometry — hidden
   8192, 64 q heads / 8 KV heads, intermediate 28672 — on a virtual
   8-device CPU mesh (TP=8), reduced to 1 layer and an 8192 vocab so
   Pallas interpret mode finishes in tool time (interpret unrolls the
   tile grid into the compiled program; 80 layers x 128k vocab would
   run for hours computing nothing extra — the per-layer programs are
   identical). Greedy decode must produce tokens and the packed-leaf
   count must be positive.

Default (no flags) runs both and writes MULTICHIP_r06.json.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

HBM_PER_DEVICE = {"v5e": 16e9, "v5p": 95e9, "v4": 32e9}

_DRYRUN_SNIPPET = r"""
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_default_matmul_precision", "highest")
import dataclasses
from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import init_params, param_logical_axes, preset
from symmetry_tpu.models.llama import quantize_params
from symmetry_tpu.parallel import MeshSpec, build_mesh, shardings_for

cfg = dataclasses.replace(preset("llama3-70b"), num_layers=1,
                          vocab_size=8192)
mesh = build_mesh(MeshSpec(data=1, model=8))
params = init_params(cfg, jax.random.key(0), jnp.bfloat16)
params = jax.device_put(params, shardings_for(param_logical_axes(cfg), mesh))
params = quantize_params(params)
eng = InferenceEngine(cfg, params, ByteTokenizer(), mesh=mesh,
                      max_slots=2, max_seq_len=64, prefill_buckets=(16,),
                      cache_dtype=jnp.bfloat16, fused_dequant=True)
from symmetry_tpu.ops.quant import PackedQuantizedTensor
packed = sum(isinstance(l, PackedQuantizedTensor)
             for l in jax.tree.leaves(
                 eng.params,
                 is_leaf=lambda x: isinstance(x, PackedQuantizedTensor)))
assert packed > 0, "fused engine packed no leaves at 70B geometry"
first = eng.prefill_and_insert(0, list(b"fit plan"), SamplingParams())
toks = [int(first)]
for _ in range(2):
    toks.append(int(eng.decode_steps()[0][0]))
assert all(0 <= t < cfg.vocab_size for t in toks), toks
print("FIT70B_DRYRUN_OK packed=%d toks=%s" % (packed, toks))
"""


def per_device_table(dcn_data: int, ici_model: int) -> dict:
    """Abstract per-device byte table — eval_shape only, zero FLOPs."""
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.models import preset
    from symmetry_tpu.models.llama import (
        QUANT_KEYS, cache_logical_axes, init_cache, init_params,
        param_logical_axes, quantized_logical_axes,
    )
    from symmetry_tpu.ops.qmm import (
        _TPU_MIN_BK, _TPU_MIN_BN, W8A16_BLOCK_K, W8A16_BLOCK_N,
        pick_w8a16_block,
    )
    from symmetry_tpu.ops.quant import QuantizedTensor
    from symmetry_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec

    cfg = preset("llama3-70b")
    axis_sizes = {"data": dcn_data, "model": ici_model}

    # Abstract trees: int8 param tree (QUANT_KEYS leaves quantize to
    # QuantizedTensor{q:int8, scale:f32}) and its logical-axes mirror.
    params = jax.eval_shape(
        lambda: init_params(cfg, jax.random.key(0), jnp.bfloat16,
                            quantize=True))
    axes = quantized_logical_axes(param_logical_axes(cfg))

    def shard_parts(logical) -> int:
        parts = 1
        for mesh_ax in logical_to_spec(logical, DEFAULT_RULES):
            if mesh_ax is not None:
                parts *= axis_sizes.get(mesh_ax, 1)
        return parts

    def leaf_bytes(leaf) -> int:
        return math.prod(leaf.shape) * leaf.dtype.itemsize

    rows = []

    def walk(node, anode, prefix):
        if isinstance(node, dict):
            for name in node:
                walk(node[name], anode[name], prefix + (name,))
            return
        path = "/".join(prefix)
        if isinstance(node, QuantizedTensor):
            parts = shard_parts(anode.q)
            total = leaf_bytes(node.q) + leaf_bytes(node.scale)
            per_dev = (leaf_bytes(node.q) // shard_parts(anode.q)
                       + leaf_bytes(node.scale) // shard_parts(anode.scale))
            # Packability at this TP: per-shard last-two dims against
            # the TPU tile floors — the same gate pack_params applies.
            *_, K, N = node.q.shape
            k_parts = shard_parts((anode.q[-2],))
            n_parts = shard_parts((anode.q[-1],))
            if K % k_parts or N % n_parts:
                verdict = "mixed_dot:shard_indivisible"
            else:
                bk = pick_w8a16_block(K // k_parts, W8A16_BLOCK_K,
                                      floor=_TPU_MIN_BK)
                bn = pick_w8a16_block(N // n_parts, W8A16_BLOCK_N,
                                      floor=_TPU_MIN_BN)
                verdict = (f"packed:bk={bk},bn={bn}"
                           if bk and bn else "mixed_dot:shard_untileable")
        else:
            parts = shard_parts(anode)
            total = leaf_bytes(node)
            per_dev = total // parts
            verdict = "dense"
        rows.append({"leaf": path, "shape": list(getattr(
            node, "q", node).shape), "bytes_total": total,
            "bytes_per_device": per_dev, "shard_parts": parts,
            "layout": verdict})

    walk(params, axes, ())

    # KV cache at the serving shape the fit question is asked for:
    # 8 slots x 8192 positions, int8 KV (tpu.kv_quant) — batch on the
    # dcn data axis, kv_heads on the ici model axis.
    slots, capacity = 8, 8192
    kv = jax.eval_shape(lambda: init_cache(cfg, slots, capacity,
                                           jnp.bfloat16, quantized=True))
    kv_axes = cache_logical_axes(quantized=True)
    kv_rows = []
    for field in kv._fields:
        leaf, logical = getattr(kv, field), getattr(kv_axes, field)
        if leaf is None:
            continue
        parts = shard_parts(logical)
        kv_rows.append({"leaf": f"kv/{field}",
                        "shape": list(leaf.shape),
                        "bytes_total": leaf_bytes(leaf),
                        "bytes_per_device": leaf_bytes(leaf) // parts,
                        "shard_parts": parts, "layout": "dense"})

    param_dev = sum(r["bytes_per_device"] for r in rows)
    kv_dev = sum(r["bytes_per_device"] for r in kv_rows)
    packed_dev = sum(r["bytes_per_device"] for r in rows
                     if r["layout"].startswith("packed"))
    return {
        "model": "llama3-70b",
        "mesh": {"dcn_data": dcn_data, "ici_model": ici_model,
                 "n_devices": dcn_data * ici_model},
        "kv_shape": {"slots": slots, "capacity": capacity,
                     "kv_quant": "int8"},
        "params_bytes_per_device": param_dev,
        "kv_bytes_per_device": kv_dev,
        "total_bytes_per_device": param_dev + kv_dev,
        "packed_bytes_per_device": packed_dev,
        "fits": {name: param_dev + kv_dev < hbm
                 for name, hbm in HBM_PER_DEVICE.items()},
        "leaves": rows + kv_rows,
    }


def run_dryrun(timeout: int = 1800) -> dict:
    """Reduced-layer 70B-geometry fused TP=8 dryrun in a subprocess
    pinned to a virtual 8-device CPU mesh (self-contained: works on a
    host whose ambient backend is a single TPU chip — same contract as
    __graft_entry__.dryrun_multichip)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
           and not k.startswith("TPU")
           and not k.startswith("PJRT")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    try:
        proc = subprocess.run([sys.executable, "-c", _DRYRUN_SNIPPET],
                              env=env, capture_output=True, text=True,
                              timeout=timeout)
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -1
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = f"timeout after {timeout}s"
    return {"rc": rc, "ok": rc == 0 and "FIT70B_DRYRUN_OK" in out,
            "stdout_tail": out[-500:], "stderr_tail": err[-500:]}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--table", action="store_true",
                    help="byte table only (skip the dryrun)")
    ap.add_argument("--dryrun", action="store_true",
                    help="dryrun only (skip the byte table)")
    ap.add_argument("--dcn-data", type=int, default=2)
    ap.add_argument("--ici-model", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="write the combined JSON here "
                         "(default MULTICHIP_r06.json at the repo root)")
    args = ap.parse_args()
    both = not (args.table or args.dryrun)

    result: dict = {"round": "r06"}
    if args.table or both:
        result["fit_table"] = per_device_table(args.dcn_data,
                                               args.ici_model)
        t = result["fit_table"]
        gb = 1 / 1e9
        print(f"[fit70b] params {t['params_bytes_per_device'] * gb:.2f} "
              f"GB/dev + kv {t['kv_bytes_per_device'] * gb:.2f} GB/dev "
              f"= {t['total_bytes_per_device'] * gb:.2f} GB/dev on "
              f"{t['mesh']['n_devices']} devices "
              f"(fits v5e-16GB: {t['fits']['v5e']})")
    if args.dryrun or both:
        print("[fit70b] dryrun: 1-layer 70B geometry, fused TP=8, "
              "8 virtual CPU devices ...", flush=True)
        result["dryrun"] = run_dryrun()
        print(f"[fit70b] dryrun ok={result['dryrun']['ok']} "
              f"rc={result['dryrun']['rc']}")
        if not result["dryrun"]["ok"]:
            print(result["dryrun"]["stderr_tail"], file=sys.stderr)
    result["ok"] = all(result[k]["ok"] if k == "dryrun"
                       else result[k]["fits"]["v5e"]
                       for k in ("fit_table", "dryrun") if k in result)

    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "MULTICHIP_r06.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(f"[fit70b] wrote {out}")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
