#!/usr/bin/env python3
"""bench_index — the capture ledger: every BENCH_*/MULTICHIP_*/
AUTOSCALE_* JSON in one fingerprint-grouped trend table.

benchdiff.py answers "did THIS run regress against THAT one"; twenty
rounds of captures also need the longitudinal answer — "what has this
metric been doing across the campaign, per configuration". This tool
indexes every capture in the repo (or --dir), groups them by
`config_fingerprint` (captures of different knob sets must never share
a trend line — the same guard benchdiff enforces pairwise), and renders
per-group trends for the policied metrics using benchdiff's own series
machinery (flatten, POLICIES, median/IQR noise bands): the newest
capture in each group gets a verdict against the median of its
predecessors, exactly like a benchdiff series run.

    python tools/bench_index.py                     # markdown to stdout
    python tools/bench_index.py --json              # JSON instead
    python tools/bench_index.py --out BENCH_INDEX.md --json-out idx.json

Pre-schema captures (no fingerprint stamp) are indexed too — grouped
per file-prefix under an `unstamped:` key so their headline numbers
stay visible — but get no verdicts: an unstamped trend line cannot
prove its runs shared a config.

Exit code 1 when any group's newest capture REGRESSED a policied
metric beyond its noise band (a CI step can gate on the index the same
way it gates on a pairwise diff), else 0.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from benchdiff import _iqr, _median, flatten, policy_for  # noqa: E402

CAPTURE_GLOBS = ("BENCH_*.json", "MULTICHIP_*.json", "AUTOSCALE_*.json")

# Trend rows are limited to policied metrics plus these always-shown
# headline leaves; unpolicied counters scale with workload size and
# would bury the table.
_HEADLINE = re.compile(r"^(value|vs_baseline|steady_state_tok_s)$")


def _round_key(path: str) -> tuple[str, int, str]:
    """Sort captures campaign-order: prefix, then round number."""
    base = os.path.basename(path)
    m = re.match(r"([A-Z_]+?)_r?(\d+)", base)
    if m:
        return (m.group(1), int(m.group(2)), base)
    return (base, 0, base)


def load_capture(path: str) -> dict | None:
    """One capture's metric dict. Smoke-runner wrappers ({"parsed":
    ...}) are unwrapped; files with no recognizable metric payload
    (fit tables, dry runs) index as headline-only."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"[bench_index] skipping {path}: {exc}", file=sys.stderr)
        return None
    if not isinstance(data, dict):
        return None
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and ("value" in parsed
                                     or "metric" in parsed):
        return parsed
    return data


def index_captures(paths: list[str]) -> dict[str, list[dict]]:
    """Group capture records by config fingerprint. Record: {file,
    fingerprint, mode, sha, written_at, headline, flat}."""
    groups: dict[str, list[dict]] = {}
    for path in sorted(paths, key=_round_key):
        cap = load_capture(path)
        if cap is None:
            continue
        fp = cap.get("config_fingerprint")
        prefix = _round_key(path)[0]
        key = str(fp) if fp else f"unstamped:{prefix}"
        groups.setdefault(key, []).append({
            "file": os.path.basename(path),
            "fingerprint": fp,
            "mode": (cap.get("config") or {}).get("mode"),
            "sha": (cap.get("git_sha") or "")[:12] or None,
            "written_at": cap.get("written_at"),
            "headline": {"metric": cap.get("metric"),
                         "value": cap.get("value"),
                         "unit": cap.get("unit")},
            "flat": flatten(cap),
        })
    return groups


def trend_rows(records: list[dict], *, judge: bool = True) -> list[dict]:
    """Per-metric trend over one fingerprint group, campaign order.
    The LAST capture is judged against the median of the earlier ones
    with the benchdiff noise band (max(min_effect x |median|, IQR));
    single-capture groups and unpolicied metrics carry no verdict, and
    judge=False (unstamped groups — config parity unproven) suppresses
    verdicts entirely so the trend stays informational."""
    flats = [r["flat"] for r in records]
    paths = sorted(set().union(*flats) if flats else ())
    rows: list[dict] = []
    for path in paths:
        series = [f.get(path) for f in flats]
        present = [v for v in series if v is not None]
        if len(present) < 1:
            continue
        pol = policy_for(path)
        if pol is None and not _HEADLINE.search(path):
            continue
        row: dict[str, Any] = {"metric": path, "series": series}
        if judge and pol is not None and len(present) >= 2:
            direction, min_effect = pol
            base = present[:-1]
            newest = present[-1]
            ref = _median(base)
            band = max(min_effect * abs(ref), _iqr(base))
            delta = newest - ref
            worse = delta < 0 if direction == "higher" else delta > 0
            row.update(
                direction=direction, median=ref,
                band=round(band, 6), delta=round(delta, 6),
                verdict=("ok" if abs(delta) <= band
                         else "REGRESSED" if worse else "improved"))
        rows.append(row)
    order = {"REGRESSED": 0, "improved": 1, "ok": 2, None: 3}
    rows.sort(key=lambda r: (order.get(r.get("verdict"), 3), r["metric"]))
    return rows


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return str(int(v)) if v == int(v) and abs(v) < 1e12 else f"{v:.4g}"
    return str(v)


def render_markdown(groups: dict[str, list[dict]]) -> str:
    lines = ["# bench index", ""]
    n_caps = sum(len(v) for v in groups.values())
    lines.append(f"{n_caps} captures in {len(groups)} fingerprint "
                 f"group(s).")
    for key in sorted(groups, key=lambda k: groups[k][0]["file"]):
        records = groups[key]
        head = records[-1]["headline"]
        lines += ["", f"## `{key}`", ""]
        lines.append("- captures: " + ", ".join(
            f"`{r['file']}`" + (f" @ `{r['sha']}`" if r["sha"] else "")
            for r in records))
        if head.get("metric"):
            lines.append(f"- latest headline: {head['metric']} = "
                         f"{_fmt(head.get('value'))} "
                         f"{head.get('unit') or ''}".rstrip())
        rows = trend_rows(records,
                          judge=records[0]["fingerprint"] is not None)
        if not rows:
            continue
        lines += ["", "| metric | trend | median | Δ(last) | band "
                      "| verdict |", "|---|---|---|---|---|---|"]
        for r in rows:
            trend = " → ".join(_fmt(v) for v in r["series"])
            verdict = r.get("verdict")
            lines.append(
                f"| `{r['metric']}` | {trend} | {_fmt(r.get('median'))} "
                f"| {_fmt(r.get('delta'))} | {_fmt(r.get('band'))} "
                f"| {('**' + verdict + '**') if verdict == 'REGRESSED' else (verdict or '-')} |")
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_index", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the capture JSONs (default: repo root)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the markdown table here")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the index as JSON instead of markdown")
    ap.add_argument("--json-out", default=None, metavar="PATH",
                    help="also write the JSON index here")
    args = ap.parse_args(argv)

    paths: list[str] = []
    for pattern in CAPTURE_GLOBS:
        paths.extend(glob.glob(os.path.join(args.dir, pattern)))
    if not paths:
        print(f"[bench_index] no captures under {args.dir}",
              file=sys.stderr)
        return 0
    groups = index_captures(paths)
    payload = {
        "schema": 1,
        "groups": {key: {"captures": [
                        {k: v for k, v in r.items() if k != "flat"}
                        for r in records],
                    "trends": trend_rows(
                        records,
                        judge=records[0]["fingerprint"] is not None)}
                   for key, records in groups.items()},
    }
    regressed = any(
        row.get("verdict") == "REGRESSED"
        for g in payload["groups"].values() for row in g["trends"])
    payload["regressed"] = regressed
    md = render_markdown(groups)
    if args.as_json:
        print(json.dumps(payload, indent=1))
    else:
        print(md, end="")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(md)
        print(f"[bench_index] trend table → {args.out}", file=sys.stderr)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=1)
        print(f"[bench_index] JSON index → {args.json_out}",
              file=sys.stderr)
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
