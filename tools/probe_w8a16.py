"""Probe: W8A16 fused tile-dequant matmul (ops/qmm.py w8a16_matmul) —
tile-size / buffer-depth sweep against the XLA mixed dot it would replace.

The decode convert wall (BASELINE.md rounds 3-4, tools/bisect_decode.py):
XLA's bf16×int8 mixed dot materializes a full bf16 copy of every int8
weight before each dot, pinning decode at the s8→bf16 convert throughput
(~480 GB/s effective in-trunk) instead of HBM bandwidth (740-860 GB/s
for a pure bf16 matmul). The W8A8 route was measured ~50% slower at
decode M (ops/qmm.py). This probe measures the one unattempted lever
(VERDICT r05 #8): weights streamed as pre-packed contiguous int8 tiles,
dequantized tile-by-tile in VMEM inside the pallas grid pipeline —
convert overlapped with DMA and MXU work, no full-tensor bf16 copy.

The (bk, bn) tile size is both the DMA granularity and the effective
double-buffer DEPTH lever: the pallas_call pipeline keeps the NEXT tile
in flight behind the current tile's dequant+dot, so small tiles mean a
shallow fast-turnaround pipeline (launch-bound), large tiles a deep one
(VMEM-bound). The sweep brackets both failure modes; the production
defaults (W8A16_BLOCK_K/N in ops/qmm.py) should be set from this table.

Run: python tools/probe_w8a16.py          (PROBE_M=128 by default — the
     decode slot batch; PROBE_M=1152 probes the verify-block shape)

Measured table (fill per chip; this repo's CI box is CPU-only, so the
kernel rows await the next on-chip bench round — the reference rows are
the round-3 measurements the wall was diagnosed with):

  M=128, K=4096, N=4*14336 (llama3-8b FFN-equivalent read)
  | path                         | ms/loop | eff GB/s |
  |------------------------------|---------|----------|
  | XLA mixed dot (production)   |         | ~480 in-trunk (r03)       |
  | bf16 × bf16 (the ceiling)    |         | 740-860 (r03)             |
  | w8a16 bk=256 bn=256          |         | pending on-chip round     |
  | w8a16 bk=512 bn=256          |         | pending on-chip round     |
  | w8a16 bk=512 bn=512          |         | pending on-chip round     |
  | w8a16 bk=1024 bn=512         |         | pending on-chip round     |
  | w8a16 bk=512 bn=1024         |         | pending on-chip round     |

Decision rule (BASELINE.md decode-floor section): the best kernel point
must beat the mixed dot here AND in the full trunk (`bench.py --engine
--fused-dequant`, then the driver e2e A/B) before `tpu.fused_dequant`
defaults on; a negative result is promoted as the official convert-wall
floor conclusion, closing VERDICT #8 either way.
"""

from __future__ import annotations

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_util import timeit  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from symmetry_tpu.ops.qmm import w8a16_matmul  # noqa: E402
from symmetry_tpu.ops.quant import pack_quantized, quantize  # noqa: E402


def loop(body, iters: int):
    """Carry-DEPENDENT benchmark loop (probe_s8_mxu convention): without
    the carry, XLA hoists the loop-invariant matmul out of the scan and
    the timing is fiction (observed: 905 GB/s, above HBM peak)."""

    def run(x, *w):
        def step(carry, _):
            y = body(carry, *w)
            nxt = carry + (y[:, :carry.shape[1]] * 1e-9).astype(carry.dtype)
            return nxt, ()

        out, _ = jax.lax.scan(step, x, None, length=iters)
        return out

    return jax.jit(run)


def main() -> None:
    M = int(os.environ.get("PROBE_M", 128))
    # Default: one llama3-8b layer's fused-FFN-scale read. PROBE_K/N
    # shrink it for an off-chip smoke run (interpret mode cannot afford
    # the real shapes, and its numbers are meaningless anyway).
    K = int(os.environ.get("PROBE_K", 4096))
    N = int(os.environ.get("PROBE_N", 4 * 14336))
    ITERS = int(os.environ.get("PROBE_ITERS", 20))
    interpret = jax.default_backend() != "tpu"
    if interpret:
        print("WARNING: no TPU backend — interpret mode measures the "
              "emulator, not the chip; table numbers must come from a "
              "v5e run", flush=True)

    kx, kw = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (M, K), jnp.bfloat16)
    w = jax.random.normal(kw, (K, N), jnp.float32) * 0.02
    qt = quantize(w)
    wb = jnp.asarray(w, jnp.bfloat16)
    del w

    def report(name: str, ms: float) -> None:
        gbs = K * N * ITERS / (ms / 1e3) / 1e9
        print(f"{name:24s} {ms:8.2f} ms/loop  {gbs:7.1f} GB/s", flush=True)

    # Reference 1: the production mixed dot (int8 operand passed direct).
    def mixed(x, q, s):
        y = jax.lax.dot_general(
            x, q, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (y * s).astype(x.dtype)

    report("xla-mixed (production)",
           timeit(loop(mixed, ITERS), x, qt.q, qt.scale, n=10))

    # Reference 2: pure bf16 — the HBM-bandwidth ceiling (2x the bytes).
    report("bf16 (2x bytes)",
           timeit(loop(lambda x, w: (x @ w).astype(x.dtype), ITERS), x, wb))

    # The sweep: each (bk, bn) is a different DMA granularity / pipeline
    # depth for the SAME production kernel (pack once per point — the
    # engine packs at load, so packing cost is off the decode path).
    for bk, bn in ((256, 256), (512, 256), (256, 512), (512, 512),
                   (1024, 512), (512, 1024)):
        if K % bk or N % bn:
            continue
        try:
            pt = pack_quantized(qt, bk=bk, bn=bn)
            f = loop(lambda x, q, s: w8a16_matmul(
                x, q, s, interpret=interpret), ITERS)
            report(f"w8a16 bk{bk} bn{bn}", timeit(f, x, pt.q, pt.scale,
                                                  n=10))
        except Exception as exc:  # noqa: BLE001 — sweep must finish
            print(f"w8a16 bk{bk} bn{bn} failed: "
                  f"{type(exc).__name__}: {exc}"[:300], flush=True)


if __name__ == "__main__":
    main()
