#!/usr/bin/env python3
"""CI smoke for the symledger layer (no TPU, no subprocess engines).

Phase 1 — attribution on a live scheduler: a tiny inproc engine serves
real traffic (plain prompts, a chunked long prompt, a mid-stream
cancel); every terminal event must carry a costs block (source
"blocked", finish matching the event), and the books must balance —
per-request device seconds plus the unattributed residue reconstruct
the scheduler's own dispatch walls within 5%.

Phase 2 — the fleet wire: client → server → provider on the in-memory
transport with an echo backend (source "estimated"). The final stream
frame's costs block must surface as `session.last_costs`, the provider
must fold it — `sym_request_device_seconds` and
`sym_goodput_tokens_per_device_second` in the Prometheus exposition,
a `goodput` block in stats() — and `symtop --once` must render real
COST / WASTE% / GPUT cells from the same scrape.

Phase 3 — the knob: a provider with `tpu: {ledger: false}` must ship
NO costs on the wire (`session.last_costs` is None) — the disabled
mode's one-guarded-branch contract, observable end to end.

Exit 0 on success; exit 1 with a reason otherwise.

Run: python tools/ledger_smoke.py
"""

from __future__ import annotations

import asyncio
import io
import os
import sys
import threading
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(f"[ledger_smoke] {msg}", flush=True)


def phase1_scheduler_conservation() -> None:
    import jax
    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.scheduler import GenRequest, Scheduler
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset

    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    engine = InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=96,
        prefill_buckets=(16, 48), cache_dtype=jnp.float32,
        decode_block=2, prefill_chunk=16)
    engine.warmup()
    sched = Scheduler(engine, debug_invariants=True)

    results: dict[int, list] = {0: [], 1: [], 2: []}
    done = {i: threading.Event() for i in results}
    cancel = threading.Event()
    prompts = [list(b"hello symledger"), list(b"cancelled stream"),
               # > prefill_chunk: the chunk phase gets attributed too.
               list(b"a long prompt that needs chunked prefill here..")]
    for i, ids in enumerate(prompts):
        def emit(ev, i=i):
            results[i].append(ev)
            if i == 1 and len(results[1]) >= 3:
                # Cancel from inside r1's own stream: guaranteed to
                # land mid-decode, with blocks still in flight.
                cancel.set()
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(
            prompt_ids=ids, sampling=SamplingParams(),
            max_new_tokens=24 if i != 1 else 64, emit=emit, id=f"r{i}",
            cancelled=(cancel.is_set if i == 1 else (lambda: False))))
    sched.start()
    for i, ev in done.items():
        assert ev.wait(180), f"r{i} did not complete"
    sched.stop()

    finals = {f"r{i}": evs[-1] for i, evs in results.items()}
    for rid, ev in finals.items():
        costs = ev.costs
        assert isinstance(costs, dict), \
            f"{rid} terminal event carries no costs block: {ev}"
        assert costs["source"] == "blocked", (rid, costs)
        assert costs["finish"] == ev.finish_reason, (rid, costs)
        assert costs["device_total_s"] > 0, (rid, costs)
    assert finals["r1"].finish_reason == "cancelled", finals["r1"]
    assert finals["r1"].costs["wasted_s"].get("cancelled", 0) > 0, \
        f"mid-decode cancel booked no cancelled waste: {finals['r1'].costs}"
    assert finals["r2"].costs["device_s"].get("chunk", 0) > 0, \
        f"chunked prefill not attributed: {finals['r2'].costs}"

    stats = sched.stats()
    led = stats.get("ledger")
    assert led and led["enabled"], "stats carry no ledger rider"
    m = sched.metrics
    rhs = m["admit_s"] + m["adopt_s"] + m["chunk_s"] + m["sync_s"]
    lhs = led["device_total_s"]
    assert rhs > 0 and abs(lhs - rhs) <= max(0.05 * rhs, 1e-4), \
        f"conservation broke: attributed {lhs:.6f}s vs walls {rhs:.6f}s"
    assert led["finished"] == 3 and led["live"] == 0, led
    assert len(led["ring"]) == 3, led["ring"]
    log(f"phase 1 OK: attributed {lhs * 1e3:.1f}ms vs walls "
        f"{rhs * 1e3:.1f}ms, wasted {led['wasted_total_s'] * 1e3:.2f}ms "
        f"({sorted(led['wasted_s'])})")


async def _echo_provider(hub, server_ident, name, tpu_overrides):
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.backends.echo import EchoBackend
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider

    cfg = ConfigManager(config={
        "name": name,
        "public": True,
        "serverKey": server_ident.public_hex,
        "modelName": f"echo:{name}",
        "apiProvider": "echo",
        "dataCollectionEnabled": False,
        "metrics": {"port": 0},
        # Loose-but-armed SLO targets: the echo stream meets them, so
        # the goodput fold counts its tokens as attaining.
        "slo": {"ttft_s": 30.0, "inter_chunk_s": 30.0,
                "objective": 0.99, "min_samples": 1000},
        **({"tpu": tpu_overrides} if tpu_overrides else {}),
    })
    provider = SymmetryProvider(
        cfg, transport=hub, identity=Identity.from_name(name),
        backend=EchoBackend(delay_s=0.01),
        server_address="mem://ledger-server")
    await provider.start(f"mem://{name}")
    await provider.wait_registered()
    return provider


async def phases_2_3(tmp_dir: str) -> None:
    import contextlib

    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.memory import MemoryTransport
    from symmetry_tpu.utils.metrics import parse_prometheus_text

    hub = MemoryTransport()
    server_ident = Identity.from_name("ledger-smoke-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://ledger-server")

    provider = await _echo_provider(
        hub, server_ident, "ledger-smoke-prov", None)
    assert provider.metrics_server is not None, "metrics endpoint not up"
    url = f"http://127.0.0.1:{provider.metrics_server.port}/metrics"

    client = SymmetryClient(Identity.from_name("ledger-smoke-cli"), hub)
    details = await client.request_provider(
        "mem://ledger-server", server_ident.public_key,
        "echo:ledger-smoke-prov")
    session = await client.connect(details)
    try:
        prompt = " ".join(f"w{i}" for i in range(24))
        for _ in range(2):
            text = "".join([d async for d in session.chat(
                [{"role": "user", "content": prompt}])])
            assert text == prompt, f"echo mismatch: {text[:60]!r}"
        costs = session.last_costs
        assert isinstance(costs, dict), \
            f"final frame carried no costs block: {session.last_usage}"
        assert costs["source"] == "estimated", costs
        assert costs["tokens"] > 0 and costs["device_total_s"] > 0, costs

        def _scrape_blocking() -> dict:
            with urllib.request.urlopen(url, timeout=5) as resp:
                return parse_prometheus_text(resp.read().decode())

        fams = await asyncio.to_thread(_scrape_blocking)
        fam = fams.get("sym_request_device_seconds")
        assert fam, "sym_request_device_seconds missing from exposition"
        phases = {s["labels"].get("phase") for s in fam["series"]
                  if s.get("suffix") == "_count"}
        n = sum(s["value"] for s in fam["series"]
                if s.get("suffix") == "_count")
        assert n >= 2 and phases, (n, phases)
        gp = fams.get("sym_goodput_tokens_per_device_second")
        assert gp and gp["series"][0]["value"] > 0, \
            f"goodput gauge missing or zero: {gp}"

        stats = await session.stats()
        goodput = stats.get("goodput")
        assert goodput, f"stats carry no goodput block: {sorted(stats)}"
        assert goodput["window_requests"] >= 2, goodput
        assert goodput["attained_tokens"] > 0, goodput
        log(f"phase 2 OK: {int(n)} folded requests (phases {sorted(phases)}), "
            f"goodput {goodput.get('tokens_per_device_s')} tok/dev-s")
    finally:
        await session.close()

    # symtop --once renders COST / WASTE% / GPUT from the same scrape.
    import tools.symtop as symtop

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = await asyncio.to_thread(
            symtop.main, ["--once", "--metrics-url", url])
    table = buf.getvalue()
    assert rc == 0, "symtop --once failed"
    header, row = table.splitlines()[0], table.splitlines()[1]
    assert "COST" in header and "WASTE%" in header and "GPUT" in header, \
        f"symtop header lacks ledger columns: {header!r}"

    def cell(line: str, name: str) -> str:
        # Fixed-width columns, "  " separators — slice, don't split
        # (headers like "TTFT p50" contain spaces).
        i = symtop.COLUMNS.index(name)
        start = sum(w + 2 for w in symtop.WIDTHS[:i])
        return line[start:start + symtop.WIDTHS[i]].strip()

    cost_cell, gput_cell = cell(row, "COST"), cell(row, "GPUT")
    assert cost_cell not in ("-", ""), f"COST cell empty: {row!r}"
    assert float(gput_cell) > 0, f"GPUT cell not positive: {row!r}"
    log(f"phase 2 OK: symtop row COST={cost_cell} GPUT={gput_cell}")
    await provider.stop()

    # ---- phase 3: tpu.ledger=false ships no costs ----------------------
    provider_off = await _echo_provider(
        hub, server_ident, "ledger-smoke-off", {"ledger": False})
    details = await client.request_provider(
        "mem://ledger-server", server_ident.public_key,
        "echo:ledger-smoke-off")
    session = await client.connect(details)
    try:
        text = "".join([d async for d in session.chat(
            [{"role": "user", "content": "knob off"}])])
        assert text == "knob off", text
        assert session.last_costs is None, \
            f"tpu.ledger=false still shipped costs: {session.last_costs}"
    finally:
        await session.close()
    log("phase 3 OK: tpu.ledger=false ships no costs block")
    await provider_off.stop()
    await server.stop()


def main() -> int:
    import tempfile

    try:
        phase1_scheduler_conservation()
        with tempfile.TemporaryDirectory(prefix="ledger_smoke_") as tmp:
            asyncio.new_event_loop().run_until_complete(
                asyncio.wait_for(phases_2_3(tmp), 120))
    except AssertionError as exc:
        print(f"ledger smoke FAILED: {exc}", file=sys.stderr)
        return 1
    log("all phases OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
