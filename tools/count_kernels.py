"""Count XLA computations in the compiled decode trunk (what the ~150
small-kernels-per-step claim is made of). Run on any backend."""
import os, sys, collections
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
from symmetry_tpu.models import llama

cfg = llama.preset(sys.argv[1] if len(sys.argv) > 1 else "llama3-8b")
B, T = 128, 640
params = jax.eval_shape(lambda: llama.init_params(cfg, jax.random.key(0),
                                                  jnp.bfloat16, quantize=True))
cache = jax.eval_shape(lambda: llama.init_cache(cfg, B, T, jnp.bfloat16,
                                                quantized=True))
tok = jax.ShapeDtypeStruct((B, 1), jnp.int32)
trunk = jax.jit(lambda p, t, c: llama.forward_hidden(p, cfg, t, c),
                donate_argnums=(2,))
lowered = trunk.lower(params, tok, cache)
compiled = lowered.compile()
txt = compiled.as_text()
ops = collections.Counter()
for line in txt.splitlines():
    line = line.strip()
    if line.startswith("ROOT "):
        line = line[5:]
    if "= " in line and "fusion(" in line:
        ops["fusion"] += 1
    elif "custom-call" in line and "= " in line:
        ops["custom-call"] += 1
    elif any(f"= {k}" in line for k in ("while(", "dynamic-update-slice(",
                                        "dynamic-slice(", "scatter(",
                                        "convolution(", "dot(", "copy(")):
        for k in ("while", "dynamic-update-slice", "dynamic-slice",
                  "scatter", "convolution", "dot", "copy"):
            if f"= {k}(" in line:
                ops[k] += 1
print(dict(ops))
# the while body (the layer scan) is where the per-step kernels live:
import re
bodies = re.findall(r"%while_body[^{]*\{(.*?)\n\}", txt, re.S)
for b in bodies[:1]:
    inner = collections.Counter()
    for line in b.splitlines():
        line = line.strip()
        if "fusion(" in line and "= " in line:
            inner["fusion"] += 1
        for k in ("dot(", "custom-call(", "scatter(", "copy(",
                  "dynamic-update-slice(", "dynamic-slice("):
            if f"= {k}" in line or f" {k}" in line and "= " in line:
                inner[k.rstrip("(")] += 1
    print("while body:", dict(inner))
