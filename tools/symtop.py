#!/usr/bin/env python3
"""symtop — live terminal fleet view over the telemetry layer.

Polls one-or-many providers and renders a per-provider, per-tier table:
tok/s, TTFT p50/p99, queue depth, in-flight, occupancy, shed count,
handoff-link health, and — on autoscaled pools — TARGET (the
controller's desired M×N vs the live topology, from
sym_autoscale_target_members) and SCALE (booked scaling decisions per
minute) — the operator's answer to "is the fleet healthy RIGHT NOW",
where bench.py answers "how fast was it over a run".

Two poll paths, mixable in one invocation:

  --metrics-url http://host:port/metrics     the Prometheus exposition
        endpoint (`metrics.port` in provider.yaml) — no keys, no swarm
        stack, works against anything that speaks the text format
  --provider tcp://host:port [--key HEX]     the peer wire: one metrics
        probe per poll (MessageKey.METRICS reply = stats snapshot + the
        tier-labeled registry snapshots), Noise-encrypted like any
        client — the swarm path, no open port required

Rates (tok/s, shed/s) are counter deltas between polls; the first
sample (and --once) falls back to lifetime averages over the provider's
reported uptime. Disagg providers show one sub-row per engine tier
(prefill / decode) from the `tier` label the telemetry layer carries
end to end.

Run:
    python tools/symtop.py --metrics-url http://127.0.0.1:9100/metrics
    python tools/symtop.py --provider tcp://127.0.0.1:4631 --once
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
import urllib.request
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from symmetry_tpu.utils.metrics import (  # noqa: E402
    histogram_quantile,
    parse_prometheus_text,
)

COLUMNS = ("PROVIDER", "TIER", "TOK/S", "TTFT p50", "TTFT p99",
           "QUEUE", "INFL", "OCC", "GAP%", "DEPTH", "SHED", "RESUME",
           "WASTED", "REUSED", "DUMPS", "COST", "WASTE%", "GPUT",
           "LINK", "STATE", "SHARE", "HIT", "TARGET", "SCALE")
WIDTHS = (22, 10, 9, 9, 9, 7, 6, 5, 5, 5, 7, 7, 7, 7, 6, 7, 6, 7, 6,
          9, 6, 6, 9, 6)

# sym_pool_member_state gauge encoding (engine/disagg/pool.py
# STATE_CODES) rendered back to the membership lifecycle names.
POOL_STATE_NAMES = {0: "joining", 1: "healthy", 2: "draining", 3: "lost"}


# ----------------------------------------------------- family flattening


def families_from_snapshots(snaps: list[dict]) -> dict[str, dict]:
    """Registry snapshots (the wire `metrics.snapshots` shape) → the
    same family dict parse_prometheus_text produces, extra labels
    (tier) stamped — one row builder then serves both poll paths."""
    fams: dict[str, dict] = {}
    for item in snaps or []:
        snap = item.get("snapshot") or {}
        extra = dict(item.get("labels") or {})
        for name, fam in (snap.get("families") or {}).items():
            out = fams.setdefault(
                name, {"kind": fam.get("kind", "untyped"), "series": []})
            for s in fam.get("series") or []:
                labels = {**(s.get("labels") or {}), **extra}
                if fam.get("kind") == "histogram":
                    for le, c in s.get("buckets") or []:
                        out["series"].append(
                            {"labels": {**labels, "le": str(le)},
                             "value": float(c), "suffix": "_bucket"})
                    out["series"].append({"labels": labels,
                                          "value": float(s.get("sum", 0.0)),
                                          "suffix": "_sum"})
                    out["series"].append({"labels": labels,
                                          "value": float(s.get("count", 0)),
                                          "suffix": "_count"})
                else:
                    out["series"].append({"labels": labels,
                                          "value": float(s.get("value", 0.0)),
                                          "suffix": ""})
    return fams


def _value(fams: dict, name: str, default: float | None = None,
           **labels: str) -> float | None:
    """Sum of matching plain samples (counters sum across label sets)."""
    fam = fams.get(name)
    if fam is None:
        return default
    total, hit = 0.0, False
    for s in fam["series"]:
        if s.get("suffix"):
            continue
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
            hit = True
    return total if hit else default


def _quantile(fams: dict, name: str, q: float,
              **labels: str) -> float | None:
    fam = fams.get(name)
    if fam is None:
        return None
    buckets: dict[float | str, float] = {}
    for s in fam["series"]:
        if s.get("suffix") != "_bucket":
            continue
        lab = dict(s["labels"])
        le = lab.pop("le", None)
        if le is None or not all(lab.get(k) == v
                                 for k, v in labels.items()):
            continue
        buckets[le] = buckets.get(le, 0.0) + s["value"]

    def _key(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)

    ordered = sorted(buckets.items(), key=lambda kv: _key(kv[0]))
    return histogram_quantile([(le, c) for le, c in ordered], q)


def _ledger_cost(fams: dict) -> tuple[float | None, float]:
    """(total attributed device seconds, finished-request count) from
    the sym_request_device_seconds histogram. The count is the largest
    per-phase observation count — every finished request observes each
    phase it ran, so the busiest phase (decode for almost all traffic)
    counts the requests."""
    fam = fams.get("sym_request_device_seconds")
    if fam is None:
        return None, 0.0
    total = 0.0
    counts: dict[str, float] = {}
    for s in fam["series"]:
        if s.get("suffix") == "_sum":
            total += s["value"]
        elif s.get("suffix") == "_count":
            phase = s["labels"].get("phase", "")
            counts[phase] = counts.get(phase, 0.0) + s["value"]
    return total, max(counts.values(), default=0.0)


def _tiers(fams: dict) -> list[str]:
    seen: list[str] = []
    fam = fams.get("sym_sched_occupancy") or {"series": []}
    for s in fam["series"]:
        tier = s["labels"].get("tier", "")
        if tier and tier not in seen:
            seen.append(tier)
    return seen


def _pool_rows(name: str, fams: dict) -> list[dict[str, Any]]:
    """One sub-row per elastic-pool member (disagg M×N providers):
    membership state (joining/healthy/draining/lost), link health
    derived from it, the member's share of its tier's lifetime
    placements, and HIT — the radix-cache blocks affinity placement
    predicted it would reuse there (a warm pool shows HIT climbing on
    the members sessions keep landing on; all-zero HIT under multi-turn
    load means gossip isn't arriving) — the live answer to 'who is
    taking the traffic, who just churned, and is the cache-affine
    router actually finding warm members'."""
    fam = fams.get("sym_pool_member_state")
    if fam is None:
        return []
    states: dict[tuple[str, str], float] = {}
    for s in fam["series"]:
        if s.get("suffix"):
            continue
        lab = s["labels"]
        node = lab.get("node", "")
        if node:
            states[(lab.get("tier", ""), node)] = s["value"]
    if not states:
        return []
    placements: dict[tuple[str, str], float] = {}
    totals: dict[str, float] = {}
    pfam = fams.get("sym_pool_placements_total") or {"series": []}
    for s in pfam["series"]:
        if s.get("suffix"):
            continue
        lab = s["labels"]
        key = (lab.get("tier", ""), lab.get("node", ""))
        placements[key] = placements.get(key, 0.0) + s["value"]
        totals[key[0]] = totals.get(key[0], 0.0) + s["value"]
    hits: dict[tuple[str, str], float] = {}
    hfam = fams.get("sym_pool_predicted_hit_blocks") or {"series": []}
    for s in hfam["series"]:
        if s.get("suffix"):
            continue
        lab = s["labels"]
        key = (lab.get("tier", ""), lab.get("node", ""))
        hits[key] = hits.get(key, 0.0) + s["value"]
    rows: list[dict[str, Any]] = []
    for (tier, node), code in sorted(states.items()):
        total = totals.get(tier, 0.0)
        share = (placements.get((tier, node), 0.0) / total
                 if total else None)
        state = POOL_STATE_NAMES.get(int(code), "?")
        rows.append({
            "provider": name, "tier": node, "tok_s": None,
            "ttft_p50": None, "ttft_p99": None, "queue": None,
            "in_flight": None, "occupancy": None, "shed": None,
            # membership IS link health: healthy/draining members hold
            # a live link; lost means the link (or node) is gone.
            "link": ("up" if state in ("healthy", "draining")
                     else "DOWN" if state == "lost" else "-"),
            "state": state,
            "share": f"{share * 100:.0f}%" if share is not None else None,
            "hit": hits.get((tier, node)),
        })
    return rows


# ------------------------------------------------------------- row model


def build_rows(name: str, fams: dict,
               prev: dict | None, now: float) -> list[dict[str, Any]]:
    """One provider-level row plus one sub-row per engine tier. `prev`
    is the previous poll's {"t", "tok", "shed"} for rate deltas."""
    tok = _value(fams, "sym_provider_tokens_out_total", 0.0)
    shed = _value(fams, "sym_provider_sheds_total", 0.0)
    cost_total, cost_n = _ledger_cost(fams)
    wasted_s = _value(fams, "sym_request_wasted_seconds")
    uptime = _value(fams, "sym_provider_uptime_seconds")
    decisions = _value(fams, "sym_autoscale_decisions_total")
    if prev and now > prev["t"]:
        dt = now - prev["t"]
        tok_s = max(tok - prev["tok"], 0.0) / dt
        # SHED as a rate too (sheds since the last poll): a provider
        # that shed 10k requests last week but is healthy now must not
        # look like one actively shedding. --once / the first poll fall
        # back to the lifetime total.
        shed_disp = max(shed - prev["shed"], 0.0) / dt
        # SCALE: autoscale decisions per MINUTE since the last poll
        # (spawns + drains + rebalances — holds are not booked in the
        # counter). A fleet that keeps flapping shows it here.
        scale_disp = (None if decisions is None else
                      max(decisions - prev.get("dec", 0.0), 0.0)
                      * 60.0 / dt)
    else:
        tok_s = tok / max(uptime, 1e-9) if uptime else None
        shed_disp = shed
        scale_disp = decisions  # lifetime total on the first poll
    link = _value(fams, "sym_link_connected")
    # TARGET: the autoscaler's desired topology vs what is live —
    # "live MxN>target MxN" while a decision is being actuated (or a
    # member is mid-join/drain), collapsing to one MxN at steady state.
    target = None
    tgt_p = _value(fams, "sym_autoscale_target_members", tier="prefill")
    tgt_d = _value(fams, "sym_autoscale_target_members", tier="decode")
    if tgt_p is not None or tgt_d is not None:
        live: dict[str, int] = {}
        for s in (fams.get("sym_pool_member_state")
                  or {"series": []})["series"]:
            if not s.get("suffix") and s["value"] == 1:  # healthy
                tier = s["labels"].get("tier", "")
                live[tier] = live.get(tier, 0) + 1
        live_mn = f"{live.get('prefill', 0):.0f}x{live.get('decode', 0):.0f}"
        tgt_mn = f"{tgt_p or 0:.0f}x{tgt_d or 0:.0f}"
        target = tgt_mn if live_mn == tgt_mn else f"{live_mn}>{tgt_mn}"
    rows = [{
        "provider": name, "tier": "",
        "tok_s": tok_s,
        "ttft_p50": _quantile(fams, "sym_provider_ttft_seconds", 0.50),
        "ttft_p99": _quantile(fams, "sym_provider_ttft_seconds", 0.99),
        "queue": _value(fams, "sym_provider_pending_first_token"),
        "in_flight": _value(fams, "sym_provider_in_flight"),
        "occupancy": None,
        "shed": shed_disp,
        # Stream-resumption health (PR-14 families, lifetime totals):
        # resumes served, overlap tokens the relay's dedup DROPPED
        # (work the engine redid — should stay near zero), and the
        # flight-recorder dump count (any nonzero DUMPS is a provider
        # with post-mortem evidence waiting to be read).
        "resume": _value(fams, "sym_resume_requests_total"),
        "wasted": _value(fams, "sym_resume_wasted_tokens_total"),
        "reused": None,
        "dumps": _value(fams, "sym_provider_flight_dumps_total"),
        # symledger attribution (tpu.ledger families): COST = mean
        # attributed device seconds per finished request, WASTE% =
        # share of device time spent on work no client kept (rejected
        # drafts, sheds, kills, resume overlap), GPUT = the windowed
        # SLO-goodput gauge — attaining tokens per device second, the
        # honest throughput headline.
        "cost": (cost_total / cost_n if cost_total is not None and cost_n
                 else None),
        "waste": (_fmt_pct(wasted_s / (cost_total + wasted_s))
                  if wasted_s is not None and cost_total
                  else None),
        "gput": _value(fams, "sym_goodput_tokens_per_device_second"),
        "link": (None if link is None else ("up" if link else "DOWN")),
        "state": None, "share": None,
        "target": target, "scale": scale_disp,
        "_sample": {"t": now, "tok": tok, "shed": shed or 0.0,
                    "dec": decisions or 0.0},
    }]
    for tier in _tiers(fams):
        rows.append({
            "provider": name, "tier": tier,
            "state": None, "share": None,
            "tok_s": None,
            # True engine-side TTFT (enqueue → first sampled token),
            # not dispatch wall — queue wait must show under overload.
            "ttft_p50": _quantile(fams, "sym_sched_ttft_seconds", 0.50,
                                  tier=tier),
            "ttft_p99": _quantile(fams, "sym_sched_ttft_seconds", 0.99,
                                  tier=tier),
            "queue": _value(fams, "sym_sched_queue_depth", tier=tier),
            "in_flight": None,
            "occupancy": _value(fams, "sym_sched_occupancy", tier=tier),
            # Dispatch-gap share (devprof, tier-labeled gauge): fraction
            # of on-device wall the accelerator sat idle between
            # dispatches — THE number the pipelined scheduler drives
            # toward zero. At pipeline depth >= 2 the probe's sync
            # serializes behind every in-flight block, so this reads as
            # an UPPER bound (scheduler stats() carries the same note).
            "gap": _fmt_pct(_value(fams, "sym_dispatch_gap_share",
                                   tier=tier)),
            # Live pipeline depth (blocks in flight after the last
            # scheduler iteration): 0 = idle tier, steady < configured
            # depth = the pipeline never fills (admission-bound).
            "depth": _value(fams, "sym_sched_pipeline_depth", tier=tier),
            "shed": _value(fams, "sym_sched_deadline_sheds_total",
                           tier=tier),
            # Scheduler-side resume admissions and the radix tokens
            # they reused instead of re-prefilling (reused > 0 is the
            # cheap-resume contract; 0 with RESUME > 0 means resumes
            # are paying full prefills — cache too small or misses).
            "resume": _value(fams, "sym_resume_admissions_total",
                             tier=tier),
            "wasted": None,
            "reused": _value(fams, "sym_resume_reused_tokens_total",
                             tier=tier),
            "dumps": None,
            "link": None,
        })
    rows.extend(_pool_rows(name, fams))
    return rows


def _fmt_pct(v: float | None) -> str | None:
    return None if v is None else f"{v * 100:.0f}%"


def _fmt_cell(v: Any, width: int) -> str:
    if v is None:
        s = "-"
    elif isinstance(v, float):
        s = f"{v:.2f}" if v < 100 else f"{v:.0f}"
    else:
        s = str(v)
    return s[:width].ljust(width)


def render_table(rows: list[dict[str, Any]]) -> str:
    out = ["  ".join(c.ljust(w) for c, w in zip(COLUMNS, WIDTHS))]
    for r in rows:
        cells = (r["provider"], r["tier"] or "-", r["tok_s"],
                 r["ttft_p50"], r["ttft_p99"], r["queue"], r["in_flight"],
                 r["occupancy"], r.get("gap"), r.get("depth"),
                 r["shed"], r.get("resume"),
                 r.get("wasted"), r.get("reused"), r.get("dumps"),
                 r.get("cost"), r.get("waste") or "-", r.get("gput"),
                 r["link"] or "-",
                 r.get("state") or "-", r.get("share") or "-",
                 r.get("hit"), r.get("target") or "-", r.get("scale"))
        out.append("  ".join(_fmt_cell(c, w)
                             for c, w in zip(cells, WIDTHS)))
    return "\n".join(out)


# ----------------------------------------------------------- poll sources


def poll_http(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus_text(resp.read().decode("utf-8"))


async def poll_wire(address: str, key_hex: str | None) -> dict:
    """One metrics probe over the peer wire (stats + tier-labeled
    registry snapshots ride the same reply)."""
    from symmetry_tpu.client.client import SymmetryClient

    client = SymmetryClient()
    key = bytes.fromhex(key_hex) if key_hex else None
    session = await client.connect_direct(address, provider_key=key)
    try:
        stats = await session.stats()
    finally:
        await session.close()
    return families_from_snapshots(
        (stats.get("metrics") or {}).get("snapshots") or [])


# ------------------------------------------------------------------ main


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="symtop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--metrics-url", action="append", default=[],
                    metavar="URL",
                    help="Prometheus exposition endpoint to poll "
                         "(repeatable)")
    ap.add_argument("--provider", action="append", default=[],
                    metavar="ADDR",
                    help="provider address to poll over the peer wire "
                         "(repeatable; tcp://host:port)")
    ap.add_argument("--key", action="append", default=[], metavar="HEX",
                    help="expected provider public key for the matching "
                         "--provider (positional pairing; optional)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one table and exit (CI / scripts)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit rows as JSON lines instead of the table")
    args = ap.parse_args(argv)
    if not args.metrics_url and not args.provider:
        ap.error("give at least one --metrics-url or --provider")

    targets: list[tuple[str, str, str | None]] = []
    for url in args.metrics_url:
        targets.append(("http", url, None))
    for i, addr in enumerate(args.provider):
        targets.append(("wire", addr,
                        args.key[i] if i < len(args.key) else None))

    prev: dict[str, dict] = {}
    loop = asyncio.new_event_loop()
    try:
        while True:
            now = time.monotonic()
            rows: list[dict[str, Any]] = []
            for kind, where, key in targets:
                short = where.split("//")[-1]
                try:
                    fams = (poll_http(where) if kind == "http"
                            else loop.run_until_complete(
                                asyncio.wait_for(poll_wire(where, key),
                                                 10.0)))
                except Exception as exc:  # noqa: BLE001 — show, keep polling
                    rows.append({"provider": short, "tier": "",
                                 "tok_s": None, "ttft_p50": None,
                                 "ttft_p99": None, "queue": None,
                                 "in_flight": None, "occupancy": None,
                                 "shed": None,
                                 "link": f"ERR:{type(exc).__name__}"})
                    continue
                target_rows = build_rows(short, fams, prev.get(where), now)
                sample = target_rows[0].pop("_sample", None)
                if sample:
                    prev[where] = sample
                rows.extend(target_rows)
            if args.as_json:
                print(json.dumps(rows))
            else:
                if not args.once:
                    sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                    print(f"symtop — {len(targets)} target(s), every "
                          f"{args.interval:.0f}s — "
                          f"{time.strftime('%H:%M:%S')}\n")
                print(render_table(rows))
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        loop.close()


if __name__ == "__main__":
    sys.exit(main())
