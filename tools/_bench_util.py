"""Shared timing helpers for the tools/ benchmarks.

On the remote-tunnel TPU backend, jax.block_until_ready returns once work
is ENQUEUED, not completed (observed: a 13 GB-read decode step "takes"
0.08 ms under it). Fetching a value cannot lie, so sync() forces completion
by pulling one element to the host.
"""

from __future__ import annotations

import time

import jax
import numpy as np


def sync(x) -> None:
    """Force completion of x's computation by fetching one element."""
    leaf = jax.tree.leaves(x)[0]
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))


def timeit(fn, *args, n: int = 20, warmup: int = 3) -> float:
    """Mean wall ms per call of fn(*args), warmup excluded, sync()-fenced."""
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / n * 1e3
