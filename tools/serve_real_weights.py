"""Real-weights serving demo ON the chip (round-3 verdict #5).

One command that proves the north-star claim end to end on hardware:
"loads HF weights and serves them on TPU". It

  1. authors a REAL checkpoint with `transformers.LlamaForCausalLM
     .save_pretrained` and a REAL byte-level-BPE `tokenizer.json`
     (tokenizers library) — the same independent-implementation fixtures
     tests/test_weights_real.py pins golden logits against;
  2. serves it through the FULL stack — routing server + tpu_native
     provider subprocess (engine host on the default JAX backend, i.e.
     the real TPU when one is attached) + streaming client over TCP;
  3. asserts the streamed TEXT equals transformers' own greedy
     continuation of the same rendered chat prompt, and that the wire's
     token accounting (inferenceEnded.tokens) matches exactly.

Run: python tools/serve_real_weights.py          (uses the default JAX
backend — the real chip under axon; CPU elsewhere)

The engine runs float32 with highest matmul precision so greedy argmax
agrees with torch's float32 reference — this is a correctness demo, not
a perf configuration.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_fixtures(path: str):
    """Checkpoint + tokenizer files, authored by transformers/tokenizers.
    Returns (model, tokenizer_dir). Vocab covers every tokenizer id."""
    import tokenizers
    import torch
    import transformers

    tok = tokenizers.Tokenizer(tokenizers.models.BPE(unk_token=None))
    tok.pre_tokenizer = tokenizers.pre_tokenizers.ByteLevel(
        add_prefix_space=False)
    tok.decoder = tokenizers.decoders.ByteLevel()
    trainer = tokenizers.trainers.BpeTrainer(
        vocab_size=384, special_tokens=["<|bos|>", "<|eos|>"],
        initial_alphabet=tokenizers.pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(
        ["hello world", "the quick brown fox", "symmetry on tpu",
         "user and assistant talk"], trainer)
    tok.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as fh:
        json.dump({
            "tokenizer_class": "PreTrainedTokenizerFast",
            "bos_token": "<|bos|>",
            "eos_token": "<|eos|>",
            "chat_template": (
                "{% for m in messages %}{{ m['role'] }}: {{ m['content'] }}"
                "\n{% endfor %}assistant: "),
        }, fh)

    cfg = transformers.LlamaConfig(
        vocab_size=tok.get_vocab_size(),
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attention_bias=False,
        eos_token_id=1,  # <|eos|>
    )
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return model


async def main() -> int:
    import yaml

    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.engine.tokenizer import HFTokenizer
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.tcp import TcpTransport

    workdir = tempfile.mkdtemp(prefix="symmetry_real_weights_")
    print(f"[demo] authoring HF checkpoint + tokenizer in {workdir}")
    model = build_fixtures(workdir)
    tok = HFTokenizer(workdir)

    server_ident = Identity.from_name("real-weights-server")
    server = SymmetryServer(server_ident, TcpTransport(),
                            ping_interval_s=60.0)
    await server.start("tcp://127.0.0.1:0")

    model_name = "tiny-llama-hf:demo"
    max_new = 24
    cfg = {
        "name": "real-weights-prov",
        "public": True,
        "serverKey": server_ident.public_hex,
        "serverAddress": server.address,
        "modelName": model_name,
        "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "maxConnections": 4,
        "listenHost": "127.0.0.1",
        "privateSeed": hashlib.blake2b(
            b"real-weights-demo", digest_size=32).hexdigest(),
        "tpu": {
            "checkpoint_path": workdir,
            "tokenizer_path": workdir,
            "dtype": "float32",
            "max_batch_size": 2,
            "max_seq_len": 128,
            "prefill_buckets": [32],
            "decode_block": 4,
            # fresh conversion every run: the demo is about the load path
            "warm_cache": False,
        },
    }
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as fh:
        yaml.safe_dump(cfg, fh)
        cfg_path = fh.name

    env = dict(os.environ)
    # Greedy argmax must agree with torch's float32 reference: TPU matmuls
    # default to bf16 passes, which is enough to flip a tiny model's
    # near-ties.
    env["JAX_DEFAULT_MATMUL_PRECISION"] = "highest"
    log_path = os.path.join(workdir, "provider.log")
    proc = subprocess.Popen(
        [sys.executable, "-m", "symmetry_tpu.provider", "-c", cfg_path],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, stdout=open(log_path, "w"), stderr=subprocess.STDOUT)
    print("[demo] provider starting (weight load + compile)...")
    t0 = time.monotonic()
    try:
        while server.registry.select_provider(model_name) is None:
            if proc.poll() is not None:
                print(open(log_path).read()[-2000:], file=sys.stderr)
                raise RuntimeError(f"provider exited rc={proc.returncode}")
            if time.monotonic() - t0 > 900:
                raise TimeoutError("provider never registered")
            await asyncio.sleep(1.0)
        print(f"[demo] provider registered after "
              f"{time.monotonic() - t0:.0f}s")

        messages = [{"role": "user", "content": "hello"}]
        client = SymmetryClient(Identity.from_name("real-weights-cli"),
                                TcpTransport())
        details = await client.request_provider(
            server.address, server_ident.public_key, model_name)
        session = await client.connect(details)
        deltas = []
        async for d in session.chat(messages, max_tokens=max_new,
                                    temperature=0.0):
            deltas.append(d)
        usage = dict(session.last_usage or {})
        await session.close()
        got_text = "".join(deltas)
        print(f"[demo] streamed text ({len(deltas)} chunks): {got_text!r}")
        print(f"[demo] wire usage: {usage}")

        # Golden reference: transformers' own greedy continuation of the
        # SAME rendered prompt.
        import torch

        prompt_ids = tok.apply_chat_template(messages)
        with torch.no_grad():
            out = model.generate(
                torch.tensor([prompt_ids]).long(), max_new_tokens=max_new,
                do_sample=False, use_cache=True, pad_token_id=0)
        cont = out[0, len(prompt_ids):].tolist()
        if any(t in tok.eos_ids for t in cont):
            cut = next(i for i, t in enumerate(cont) if t in tok.eos_ids)
            n_expected = cut + 1  # engine counts the EOS token it stopped at
            cont = cont[:cut]
        else:
            n_expected = len(cont)
        want_text = tok.decode(cont)
        print(f"[demo] transformers greedy: {want_text!r}")

        ok = True
        if got_text.rstrip("�") != want_text.rstrip("�"):
            print("[demo] FAIL: streamed text != transformers greedy")
            ok = False
        if int(usage.get("tokens", -1)) != n_expected:
            print(f"[demo] FAIL: wire reported {usage.get('tokens')} "
                  f"tokens, expected exactly {n_expected}")
            ok = False
        if ok:
            import jax

            backend = jax.default_backend()
            print(f"[demo] PASS: HF checkpoint served through "
                  f"server+provider+client, greedy text golden-matched, "
                  f"exact token accounting ({n_expected} tokens) — bench "
                  f"process backend: {backend} (engine host runs the same "
                  f"default backend)")
        return 0 if ok else 1
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
        os.unlink(cfg_path)
        await server.stop()


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
