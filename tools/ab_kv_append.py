"""A/B the fused KV-append kernel in the full decode trunk on-chip."""
import os, sys

# The kernel is OPT-IN (measured HBM cost in the decode scan — see
# ops/kv_append.py supports()); without this the tool measures OFF vs OFF.
os.environ["SYMMETRY_KV_APPEND"] = "1"
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
from tools.bisect_decode import time_trunk
from symmetry_tpu.models import llama

cfg = llama.preset("llama3-8b")
B, T = 128, int(sys.argv[1]) if len(sys.argv) > 1 else 640
params = llama.init_params(cfg, jax.random.key(0), jnp.bfloat16, quantize=True)

os.environ["SYMMETRY_NO_KV_APPEND"] = "1"
off = time_trunk(cfg, params, B, T)
print(f"kv_append OFF: {off:7.2f} ms", flush=True)
del os.environ["SYMMETRY_NO_KV_APPEND"]
on = time_trunk(cfg, params, B, T)
print(f"kv_append ON:  {on:7.2f} ms  ({off - on:+.2f})", flush=True)
