import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
from symmetry_tpu.models import llama
cfg = llama.preset("llama3-8b")
B, T = 128, 640
params = jax.eval_shape(lambda: llama.init_params(cfg, jax.random.key(0), jnp.bfloat16, quantize=True))
cache = jax.eval_shape(lambda: llama.init_cache(cfg, B, T, jnp.bfloat16, quantized=True))
tok = jax.ShapeDtypeStruct((B, 1), jnp.int32)
trunk = jax.jit(lambda p, t, c: llama.forward_hidden(p, cfg, t, c), donate_argnums=(2,))
open("/tmp/trunk_hlo.txt", "w").write(trunk.lower(params, tok, cache).compile().as_text())
print("written")
