"""CI chaos smoke: a mid-stream injected crash must complete via failover.

Runs the full client → server → two-echo-provider path on the in-memory
transport, arms ONE fault — `provider.relay=error@nth=3`, which kills the
serving provider's third chunk relay and drops the client cold (the
injected stand-in for a provider process dying mid-stream) — and asserts:

  - the first provider actually streamed before dying (the fault landed
    MID-stream, not at admission);
  - chat_failover recovers on the second provider with exactly one
    ChatRestart sentinel and byte-identical final text;
  - the fault accounting (provider stats `faults` block) confirms the
    seam fired exactly once.

Then the no-op contract: with no faults configured, an instrumented seam
must cost one attribute read — 200k guarded hits in well under half a
second (order-of-magnitude headroom on CI machines) and zero behavior.

Exit 0 on success; exit 1 with a reason otherwise.

Run: python tools/chaos_smoke.py
"""

from __future__ import annotations

import asyncio
import sys
import time


async def run() -> int:
    from symmetry_tpu.client.client import ChatRestart, SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.utils.faults import FAULTS
    from symmetry_tpu.transport.memory import MemoryTransport

    hub = MemoryTransport()
    server_ident = Identity.from_name("chaos-smoke-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://server")

    def provider_cfg(name):
        return ConfigManager(config={
            "name": name, "public": True,
            "serverKey": server_ident.public_hex,
            "modelName": "echo:chaos", "apiProvider": "echo",
            "dataCollectionEnabled": False,
            "flightRecorder": {"enabled": False},
        })

    providers = []
    for name in ("chaos-smoke-p1", "chaos-smoke-p2"):
        prov = SymmetryProvider(
            provider_cfg(name), transport=hub,
            identity=Identity.from_name(name),
            server_address="mem://server")
        await prov.start(f"mem://{name}")
        await prov.wait_registered()
        providers.append(prov)
    p1, p2 = providers
    # Steer the first assignment to p1 deterministically.
    server.registry.set_connections(p2.identity.public_hex, 5)

    # The injected mid-stream crash: the serving provider's 3rd chunk
    # relay raises InjectedFault, which the provider treats as its own
    # death for that client — connection dropped, no error frame. nth
    # counts GLOBAL seam hits in this process, so after it fires on p1
    # the survivor streams clean.
    FAULTS.load("provider.relay=error(injected mid-stream crash)@nth=3")

    prompt = "the quick brown fox jumps over the lazy dog"
    client = SymmetryClient(Identity.from_name("chaos-smoke-cli"), hub)
    events = []
    async for item in client.chat_failover(
            "mem://server", server_ident.public_key, "echo:chaos",
            [{"role": "user", "content": prompt}]):
        events.append(item)

    restarts = [e for e in events if isinstance(e, ChatRestart)]
    assert len(restarts) == 1, f"expected 1 failover restart, got {restarts}"
    assert restarts[0].provider_key == p2.identity.public_hex, \
        "failover did not land on the survivor"
    cut = events.index(restarts[0])
    pre = [e for e in events[:cut] if isinstance(e, str)]
    assert pre, "fault fired before ANY chunk streamed — not mid-stream"
    final = "".join(e for e in events[cut + 1:] if isinstance(e, str))
    assert final == prompt, f"completion mismatch after failover: {final!r}"
    fired = p1.stats().get("faults", {}).get("provider.relay", {})
    assert fired.get("fired") == 1, f"relay seam accounting wrong: {fired}"
    print(f"chaos smoke: crash after {len(pre)} chunk(s) on p1; "
          f"failover completed {len(final)} chars on p2")

    FAULTS.clear()
    for prov in providers:
        await prov.stop(drain_timeout_s=1)
    await server.stop()

    # ---- no-op overhead contract --------------------------------------
    assert FAULTS.enabled is False
    t0 = time.perf_counter()
    for _ in range(200_000):
        if FAULTS.enabled and FAULTS.point("provider.relay"):
            pass
    dt = time.perf_counter() - t0
    assert dt < 0.5, f"unconfigured seam overhead too high: {dt:.3f}s/200k"
    print(f"chaos smoke: unconfigured seam = {dt / 200_000 * 1e9:.1f}ns/hit "
          f"(200k guarded hits in {dt * 1e3:.1f}ms)")
    return 0


def main() -> int:
    try:
        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run(), 120))
    except AssertionError as exc:
        print(f"chaos smoke FAILED: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
