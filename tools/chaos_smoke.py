"""CI chaos smoke: a mid-stream injected crash must complete via failover.

Runs the full client → server → two-echo-provider path on the in-memory
transport, arms ONE fault — `provider.relay=error@nth=3`, which kills the
serving provider's third chunk relay and drops the client cold (the
injected stand-in for a provider process dying mid-stream) — and asserts:

  - the first provider actually streamed before dying (the fault landed
    MID-stream, not at admission);
  - chat_failover (legacy resume=False mode) recovers on the second
    provider with exactly one ChatRestart sentinel and byte-identical
    final text;
  - the fault accounting (provider stats `faults` block) confirms the
    seam fired exactly once.

Phase 5 (stream resumption, PR 14): the same mid-stream crash with the
DEFAULT resume mode — chat_failover reissues a `resume` request on the
survivor, exactly one ChatResume (zero ChatRestart), and the SPLICED
transcript (pre-crash deltas + continuation) is byte-identical to an
uninterrupted completion; then the same drill against a fake-host
tpu_native provider asserts the resume admission reused cached tokens
(`tokens_reused > 0` — a cheap seeded re-prefill, not a full
regeneration) and that the crash shed carried the journal's emitted
count.

Then the no-op contract: with no faults configured, an instrumented seam
must cost one attribute read — 200k guarded hits in well under half a
second (order-of-magnitude headroom on CI machines) and zero behavior.

Exit 0 on success; exit 1 with a reason otherwise.

Run: python tools/chaos_smoke.py
"""

from __future__ import annotations

import asyncio
import os
import sys
import time


async def run() -> int:
    from symmetry_tpu.client.client import ChatRestart, SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.utils.faults import FAULTS
    from symmetry_tpu.transport.memory import MemoryTransport

    hub = MemoryTransport()
    server_ident = Identity.from_name("chaos-smoke-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://server")

    def provider_cfg(name):
        return ConfigManager(config={
            "name": name, "public": True,
            "serverKey": server_ident.public_hex,
            "modelName": "echo:chaos", "apiProvider": "echo",
            "dataCollectionEnabled": False,
            "flightRecorder": {"enabled": False},
        })

    providers = []
    for name in ("chaos-smoke-p1", "chaos-smoke-p2"):
        prov = SymmetryProvider(
            provider_cfg(name), transport=hub,
            identity=Identity.from_name(name),
            server_address="mem://server")
        await prov.start(f"mem://{name}")
        await prov.wait_registered()
        providers.append(prov)
    p1, p2 = providers
    # Steer the first assignment to p1 deterministically.
    server.registry.set_connections(p2.identity.public_hex, 5)

    # The injected mid-stream crash: the serving provider's 3rd chunk
    # relay raises InjectedFault, which the provider treats as its own
    # death for that client — connection dropped, no error frame. nth
    # counts GLOBAL seam hits in this process, so after it fires on p1
    # the survivor streams clean.
    FAULTS.load("provider.relay=error(injected mid-stream crash)@nth=3")

    prompt = "the quick brown fox jumps over the lazy dog"
    client = SymmetryClient(Identity.from_name("chaos-smoke-cli"), hub)
    events = []
    # resume=False pins the LEGACY discard-and-restart semantics (the
    # default resume path gets its own phase below).
    async for item in client.chat_failover(
            "mem://server", server_ident.public_key, "echo:chaos",
            [{"role": "user", "content": prompt}], resume=False):
        events.append(item)

    restarts = [e for e in events if isinstance(e, ChatRestart)]
    assert len(restarts) == 1, f"expected 1 failover restart, got {restarts}"
    assert restarts[0].provider_key == p2.identity.public_hex, \
        "failover did not land on the survivor"
    cut = events.index(restarts[0])
    pre = [e for e in events[:cut] if isinstance(e, str)]
    assert pre, "fault fired before ANY chunk streamed — not mid-stream"
    final = "".join(e for e in events[cut + 1:] if isinstance(e, str))
    assert final == prompt, f"completion mismatch after failover: {final!r}"
    fired = p1.stats().get("faults", {}).get("provider.relay", {})
    assert fired.get("fired") == 1, f"relay seam accounting wrong: {fired}"
    print(f"chaos smoke: crash after {len(pre)} chunk(s) on p1; "
          f"failover completed {len(final)} chars on p2")

    # ---- phase 5a: mid-stream crash → RESUME → spliced transcript ----
    # Same kill, default resume mode: the survivor CONTINUES from the
    # last received token instead of regenerating, and the client-side
    # splice is byte-identical to an uninterrupted run.
    from symmetry_tpu.client.client import ChatResume

    server.registry.set_connections(p1.identity.public_hex, 0)
    server.registry.set_connections(p2.identity.public_hex, 5)
    FAULTS.clear()
    FAULTS.load("provider.relay=error(injected mid-stream crash)@nth=3")
    events = []
    async for item in client.chat_failover(
            "mem://server", server_ident.public_key, "echo:chaos",
            [{"role": "user", "content": prompt}]):
        events.append(item)
    resumes = [e for e in events if isinstance(e, ChatResume)]
    assert len(resumes) == 1, f"expected 1 resume, got {events}"
    assert not any(isinstance(e, ChatRestart) for e in events), \
        "resume mode must not restart"
    assert resumes[0].provider_key == p2.identity.public_hex, \
        "resume did not land on the survivor"
    cut = events.index(resumes[0])
    pre = "".join(e for e in events[:cut] if isinstance(e, str))
    post = "".join(e for e in events[cut:] if isinstance(e, str))
    assert pre, "fault fired before ANY chunk streamed — not mid-stream"
    assert pre + post == prompt, \
        f"spliced transcript not byte-identical: {pre + post!r}"
    from symmetry_tpu.utils.metrics import METRICS

    fams = METRICS.snapshot(compact=True).get("families", {})
    res = fams.get("sym_resume_requests_total", {})
    accepted = sum(s.get("value", 0) for s in res.get("series", [])
                   if s.get("labels", {}).get("outcome") == "accepted")
    assert accepted >= 1, f"resume counter not booked: {res}"
    print(f"chaos smoke: phase 5a resume spliced {len(pre)}+{len(post)} "
          f"chars byte-identical on p2")

    FAULTS.clear()
    for prov in providers:
        await prov.stop(drain_timeout_s=1)
    await server.stop()

    # ---- phase 5b: fake-host tpu_native resume reuses cached tokens --
    # The engine-shaped leg: a supervised fake host crashes mid-stream
    # (restarting shed stamped with the journal's emitted count), the
    # resume submit streams only the continuation, and the resume
    # admission reports tokens_reused > 0.
    from symmetry_tpu.provider.backends.base import (
        BackendRestartingError, InferenceRequest)
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend

    fake_host = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fake_host.py")

    class FakeHostBackend(TpuNativeBackend):
        def _host_argv(self, cfg_path):
            return [sys.executable, fake_host, cfg_path]

    cfg = ConfigManager(config={
        "name": "chaos-resume", "public": False, "serverKey": "00" * 32,
        "modelName": "fake:resume", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        # Life 1: ready + clock×5 = 6 startup writes; nth=11 kills the
        # host on the stream's 5th event — mid-stream, 4 events relayed.
        "faults": {"host.pipe_write": "crash@nth=11"},
        "tpu": {"engine_isolation": "process", "max_batch_size": 4,
                "supervisor": {"heartbeat_s": 0.2, "wedge_timeout_s": 1.0,
                               "backoff_base_s": 0.05,
                               "backoff_max_s": 0.2, "max_respawns": 3,
                               "spawn_timeout_s": 15.0,
                               "stop_grace_s": 0.5}},
    })
    backend = FakeHostBackend(cfg)
    await backend.start()
    try:
        got = []
        emitted_stamp = None
        try:
            async for chunk in backend.stream(InferenceRequest(
                    messages=[{"role": "user", "content": "x"}],
                    max_tokens=40)):
                if chunk.text:
                    got.append(chunk.text)
        except BackendRestartingError as exc:
            emitted_stamp = exc.emitted
        assert got, "fake-host crash landed before anything streamed"
        assert emitted_stamp == len(got), \
            f"journal stamp {emitted_stamp} != relayed {len(got)}"
        # Wait out the respawn, then resume from the received text.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if backend._proc is not None and not backend._host_dead \
                    and not backend._restarting:
                break
            await asyncio.sleep(0.05)
        full = [f"t{i} " for i in range(39)]
        cont = []
        async for chunk in backend.stream(InferenceRequest(
                messages=[{"role": "user", "content": "x"}],
                max_tokens=40, resume_text="".join(got),
                resume_tokens=len(got))):
            if chunk.text:
                cont.append(chunk.text)
        assert got + cont == full, \
            f"resumed transcript diverged: {got + cont!r}"
        assert backend.resume_stats["resumes"] == 1
        assert backend.resume_stats["reused_tokens"] > 0, \
            "resume admission did not reuse cached tokens"
        print(f"chaos smoke: phase 5b fake-host resume "
              f"{len(got)}+{len(cont)} events, emitted stamp "
              f"{emitted_stamp}, reused "
              f"{backend.resume_stats['reused_tokens']} tokens")
    finally:
        await backend.stop()
    FAULTS.clear()

    # ---- no-op overhead contract --------------------------------------
    assert FAULTS.enabled is False
    t0 = time.perf_counter()
    for _ in range(200_000):
        if FAULTS.enabled and FAULTS.point("provider.relay"):
            pass
    dt = time.perf_counter() - t0
    assert dt < 0.5, f"unconfigured seam overhead too high: {dt:.3f}s/200k"
    print(f"chaos smoke: unconfigured seam = {dt / 200_000 * 1e9:.1f}ns/hit "
          f"(200k guarded hits in {dt * 1e3:.1f}ms)")
    return 0


def main() -> int:
    try:
        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run(), 120))
    except AssertionError as exc:
        print(f"chaos smoke FAILED: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
