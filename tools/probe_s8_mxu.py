"""Probe: can a Pallas kernel drive the MXU with native s8 x s8 matmuls?

XLA's mixed/int8 dot_generals all measure ~270-480 GB/s effective — the
s8->float convert throughput, not HBM bandwidth (tools/microbench_matmul).
If Mosaic emits native int8 MXU ops, a hand kernel should stream weights
at ~819 GB/s with s32 accumulation and no convert. This decides whether a
quantized-matmul kernel is worth building into the decode path.

Run: python tools/probe_s8_mxu.py
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_util import timeit  # noqa: E402


def matmul_kernel(x_ref, w_ref, o_ref, acc_scr, *, n_k: int, out_dtype):
    """One [M, bk] x [bk, bn] tile product per grid step, K innermost."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        x_ref[:], w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=acc_scr.dtype)

    @pl.when(k == n_k - 1)
    def _():
        o_ref[:] = acc_scr[:].astype(out_dtype)


def pallas_matmul(x, w, *, bn=512, bk=1024, acc_dtype=jnp.int32):
    M, K = x.shape
    K2, N = w.shape
    n_k = K // bk
    grid = (N // bn, n_k)
    return pl.pallas_call(
        functools.partial(matmul_kernel, n_k=n_k, out_dtype=jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((M, bk), lambda n, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.int32),
        scratch_shapes=[pltpu.VMEM((M, bn), acc_dtype)],
    )(x, w)


def main():
    import os
    B, E, H = int(os.environ.get('PROBE_M', 128)), 4096, 4 * 14336
    ITERS = 20

    xq = jnp.ones((B, E), jnp.int8)
    wq = jnp.ones((E, H), jnp.int8)
    xb = jnp.ones((B, E), jnp.bfloat16)
    wb = jnp.ones((E, H), jnp.bfloat16)

    def loop(body):
        """Carry-DEPENDENT input: without it XLA hoists the loop-invariant
        matmul out of the scan and the timing is fiction (observed: "bf16"
        at 905 GB/s, above HBM peak)."""
        def run(x, w):
            def step(carry, _):
                y = body(carry, w)
                nxt = (x ^ (y[:, :x.shape[1]] & 1).astype(jnp.int8)
                       if x.dtype == jnp.int8
                       else x + (y[:, :x.shape[1]] * 1e-9).astype(x.dtype))
                return nxt, ()
            out, _ = jax.lax.scan(step, x, None, length=ITERS)
            return out
        return jax.jit(run)

    def report(name, ms, nbytes):
        gbs = nbytes * ITERS / (ms / 1e3) / 1e9
        print(f"{name:18s} {ms:8.2f} ms/loop  {gbs:7.1f} GB/s", flush=True)

    for bn, bk in ((256, 512), (512, 1024), (256, 512), (512, 1024),
                   (256, 512), (512, 1024)):
        try:
            f = loop(lambda x, w, bn=bn, bk=bk: pallas_matmul(
                x, w, bn=bn, bk=bk))
            report(f"s8s8 bn{bn} bk{bk}", timeit(f, xq, wq, n=10), E * H)
        except Exception as exc:  # noqa: BLE001
            print(f"s8s8 bn{bn} bk{bk} failed: "
                  f"{type(exc).__name__}: {exc}"[:300], flush=True)

    try:
        f = loop(lambda x, w: pallas_matmul(
            x, w, acc_dtype=jnp.float32))
        report("pallas-bf16", timeit(f, xb, wb, n=10), 2 * E * H)
    except Exception as exc:  # noqa: BLE001
        print(f"pallas-bf16 failed: {type(exc).__name__}: {exc}"[:500],
              flush=True)

    # mixed: s8 weight converted in-kernel (Mosaic's convert, VMEM-resident)
    def mixed_kernel(x_ref, w_ref, o_ref, acc_scr, *, n_k):
        k = pl.program_id(1)

        @pl.when(k == 0)
        def _():
            acc_scr[:] = jnp.zeros_like(acc_scr)

        acc_scr[:] += jax.lax.dot_general(
            x_ref[:], w_ref[:].astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        @pl.when(k == n_k - 1)
        def _():
            o_ref[:] = acc_scr[:]

    def mixed(x, w, bn=512, bk=1024):
        M, K = x.shape
        _, N = w.shape
        n_k = K // bk
        return pl.pallas_call(
            functools.partial(mixed_kernel, n_k=n_k),
            grid=(N // bn, n_k),
            in_specs=[
                pl.BlockSpec((M, bk), lambda n, k: (0, k)),
                pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
            ],
            out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n)),
            out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
            scratch_shapes=[pltpu.VMEM((M, bn), jnp.float32)],
        )(x, w)

    try:
        f = loop(mixed)
        report("pallas-mixed", timeit(f, xb, wq, n=10), E * H)
    except Exception as exc:  # noqa: BLE001
        print(f"pallas-mixed failed: {type(exc).__name__}: {exc}"[:500],
              flush=True)


if __name__ == "__main__":
    main()
