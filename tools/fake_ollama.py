"""A minimal OpenAI-compatible streaming backend (fake Ollama).

Serves POST {apiPath} with `stream: true`, emitting `max_tokens` SSE
chunks in the OpenAI chat.completion.chunk dialect the proxy backend
parses (symmetry_tpu/provider/backends/proxy.py; reference hot loop
src/provider.ts:240-258). Used by `bench.py --proxy` to measure the PR1
reference point — the reference's own architecture (P2P glue around an
external HTTP inference server) — without needing a real Ollama install:
the fake emits instantly (token_delay_s=0), so the measured number is the
proxy/wire path's own overhead ceiling, not the model's speed.

Standalone: python tools/fake_ollama.py [--port 11434] [--delay 0.0]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time


def make_app(token_delay_s: float = 0.0):
    from aiohttp import web

    async def chat(request: "web.Request") -> "web.StreamResponse":
        body = await request.json()
        n = int(body.get("max_tokens") or 64)
        model = body.get("model", "fake")
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        created = int(time.time())
        for i in range(n):
            chunk = {
                "id": "chatcmpl-fake", "object": "chat.completion.chunk",
                "created": created, "model": model,
                "choices": [{"index": 0,
                             "delta": {"content": f"tok{i} "},
                             "finish_reason": None}],
            }
            await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
            if token_delay_s:
                await asyncio.sleep(token_delay_s)
        final = {"id": "chatcmpl-fake", "object": "chat.completion.chunk",
                 "created": created, "model": model,
                 "choices": [{"index": 0, "delta": {},
                              "finish_reason": "stop"}]}
        await resp.write(f"data: {json.dumps(final)}\n\n".encode())
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    app = web.Application()
    # Accept any path: the provider config points apiPath wherever.
    app.router.add_post("/{tail:.*}", chat)
    return app


async def start_server(host: str = "127.0.0.1", port: int = 0,
                       token_delay_s: float = 0.0):
    """Returns (runner, bound_port); `await runner.cleanup()` to stop."""
    from aiohttp import web

    runner = web.AppRunner(make_app(token_delay_s))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    bound = site._server.sockets[0].getsockname()[1]
    return runner, bound


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=11434)
    ap.add_argument("--delay", type=float, default=0.0,
                    help="seconds between chunks (0 = flat out)")
    args = ap.parse_args()

    async def run() -> None:
        _, port = await start_server(args.host, args.port, args.delay)
        print(f"fake ollama listening on http://{args.host}:{port}")
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
