#!/usr/bin/env python3
"""CI smoke for the overlapped scheduler pipeline (no TPU, no network).

Phase 1 — token identity across pipeline depths: the SAME mixed traffic
(greedy + seeded sampled, radix-hitting shared prefixes + cold misses,
speculation enabled with an LM-head bias that actually drafts) runs on
a tiny CPU engine at pipeline_depth 1 (the pre-pipeline double buffer)
and 2 (the overlapped default), two waves each so wave 2 re-admits
through warm radix hits. Every request's event stream must match across
depths — text, token ids, generated/emitted counts, finish reason — in
strict per-request order, the speculative counters must agree exactly,
and NEITHER depth may compile anything after its first wave
(compile_cache_sizes pinned between waves = zero steady-state
recompiles).

Phase 2 — the split the tentpole promises: depth-2 stats must carry the
dispatch-thread vs offloaded wall split, the configured + live depth
gauges, the emit-queue depth, and evidence the emit worker actually
absorbed work (offloaded_s > 0, flushes > 0).

Phase 3 — bench.py --pipeline-depth: the smoke-mode bench accepts the
knob at depths 1 and 2 and stamps pipeline_depth +
dispatch_thread_block_s into its capture; the two captures'
config_fingerprints must DIFFER so benchdiff refuses a cross-depth diff
unless --force'd (the deliberate A/B path).

Run: python tools/overlap_smoke.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(f"[overlap_smoke] {msg}", flush=True)


# Shared prefix long enough to span whole radix blocks (prefix_block 8)
# so the second admission of the pair reuses cached KV; the loner prompt
# shares nothing and stays a miss on wave 1.
_BASE = list(b"shared prefix radix AAAA")
_PROMPTS = [
    _BASE + list(b" one"),
    _BASE + list(b" two"),
    list(b"a completely different cold prompt"),
    list(b"x!"),
]


def _requests():
    from symmetry_tpu.engine.engine import SamplingParams

    reqs = [(p, SamplingParams(), 24) for p in _PROMPTS]
    # One seeded sampled stream rides along: depth must not perturb the
    # per-slot RNG chain either (same host decisions => same draws).
    reqs.append((list(b"seeded sampled stream"),
                 SamplingParams(temperature=0.8, top_k=8, seed=1234), 24))
    return reqs


def _run_wave(sched, reqs, wave: int):
    from symmetry_tpu.engine.scheduler import GenRequest

    results = {i: [] for i in range(len(reqs))}
    done = {i: threading.Event() for i in range(len(reqs))}
    for i, (ids, sampling, max_new) in enumerate(reqs):
        def emit(ev, i=i):
            results[i].append(ev)
            if ev.done:
                done[i].set()
        sched.submit(GenRequest(prompt_ids=list(ids), sampling=sampling,
                                max_new_tokens=max_new, emit=emit,
                                id=f"w{wave}r{i}"))
    for i, ev in done.items():
        assert ev.wait(180), f"wave {wave} request {i} did not complete"
    return results


def _signature(events):
    """Order-sensitive identity signature of one request's stream."""
    text = "".join(ev.text for ev in events)
    ids = [ev.token_id for ev in events if ev.token_id is not None]
    last = events[-1]
    return (text, ids, last.tokens_generated, last.tokens_emitted,
            last.finish_reason)


def _check_order(events, label: str) -> None:
    assert events, f"{label}: no events"
    assert events[-1].done, f"{label}: last event is not done"
    assert sum(1 for ev in events if ev.done) == 1, \
        f"{label}: more than one done event"
    gen = [ev.tokens_generated for ev in events]
    assert gen == sorted(gen), \
        f"{label}: tokens_generated not monotonic: {gen}"


def _run_depth(depth: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from symmetry_tpu.engine.engine import InferenceEngine
    from symmetry_tpu.engine.scheduler import Scheduler
    from symmetry_tpu.engine.spec import SpecConfig
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset

    cfg = preset("tiny")
    params = init_params(cfg, jax.random.key(0), jnp.float32)
    # Bias the LM head toward one token so the n-gram drafter matches
    # often enough to drive real verify dispatches through the pipeline
    # (the test_spec.py cycling idiom).
    lm = np.array(params["lm_head"])
    lm[:, 120] = 10.0
    params = dict(params)
    params["lm_head"] = jnp.asarray(lm)

    engine = InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=128,
        prefill_buckets=(16, 48), cache_dtype=jnp.float32,
        decode_block=4, prefill_chunk=16,
        prefix_cache_bytes=8 * 2**20, prefix_block_tokens=8,
        speculative=SpecConfig(k_draft=4))
    engine.warmup()
    sched = Scheduler(engine, debug_invariants=True, pipeline_depth=depth)
    sched.start()
    try:
        reqs = _requests()
        wave1 = _run_wave(sched, reqs, 1)
        sizes1 = engine.compile_cache_sizes()
        wave2 = _run_wave(sched, reqs, 2)
        sizes2 = engine.compile_cache_sizes()
    finally:
        sched.stop()
    assert sizes1 == sizes2, \
        (f"depth {depth}: steady-state recompile between waves: "
         f"{sizes1} -> {sizes2}")
    stats = sched.stats()
    for wave, results in (("w1", wave1), ("w2", wave2)):
        for i, events in results.items():
            _check_order(events, f"depth {depth} {wave} r{i}")
    sigs = {wave: {i: _signature(evs) for i, evs in results.items()}
            for wave, results in (("w1", wave1), ("w2", wave2))}
    return sigs, stats


def phase1_identity():
    sigs1, stats1 = _run_depth(1)
    sigs2, stats2 = _run_depth(2)
    for wave in ("w1", "w2"):
        for i in sigs1[wave]:
            assert sigs1[wave][i] == sigs2[wave][i], (
                f"depth 1 vs 2 diverged on {wave} r{i}:\n"
                f"  depth1={sigs1[wave][i]}\n  depth2={sigs2[wave][i]}")
    # The identity claim must not be vacuous: both depths drove real
    # speculative verify traffic and real radix reuse, identically.
    for stats, d in ((stats1, 1), (stats2, 2)):
        spec = stats.get("speculative") or {}
        assert spec.get("verify_blocks", 0) > 0, \
            f"depth {d}: no verify blocks ran — spec path unexercised"
        assert spec.get("drafted", 0) > 0, f"depth {d}: nothing drafted"
        pc = stats.get("prefix_cache") or {}
        assert pc.get("hits", 0) > 0, f"depth {d}: no radix hits"
        assert pc.get("misses", 0) > 0, f"depth {d}: no radix misses"
    s1, s2 = stats1["speculative"], stats2["speculative"]
    for key in ("verify_blocks", "drafted", "accepted", "rolled_back"):
        assert s1[key] == s2[key], \
            f"speculative counter {key} differs: {s1[key]} vs {s2[key]}"
    log(f"phase 1 OK: {len(sigs1['w1'])} streams x 2 waves identical at "
        f"depth 1 and 2 (spec: {s1['verify_blocks']} verify blocks, "
        f"{s1['accepted']}/{s1['drafted']} accepted; zero recompiles)")
    return stats1, stats2


def phase2_split(stats1, stats2) -> None:
    assert stats1["pipeline_depth"] == 1, stats1["pipeline_depth"]
    assert stats2["pipeline_depth"] == 2, stats2["pipeline_depth"]
    for stats, d in ((stats1, 1), (stats2, 2)):
        assert "pipeline_live_depth" in stats, f"depth {d}: no live gauge"
        assert "emit_queue_depth" in stats, f"depth {d}: no queue gauge"
        assert stats.get("dispatch_thread_s", 0) > 0, \
            f"depth {d}: no dispatch-thread wall recorded"
        assert stats.get("emit_flushes", 0) > 0, f"depth {d}: no flushes"
        dtb = stats.get("dispatch_thread_block_s") or {}
        assert dtb.get("p50") is not None, \
            f"depth {d}: no dispatch-thread block histogram"
    # Depth 1 is the pre-pipeline A/B baseline: emit stays INLINE on the
    # engine thread (zero offloaded wall); depth 2's emit worker must
    # have actually absorbed the per-block work.
    assert stats1.get("offloaded_s", 0) == 0, \
        f"depth 1 offloaded work ({stats1['offloaded_s']}s) — the A/B " \
        f"baseline must keep the inline emit path"
    assert stats2.get("offloaded_s", 0) > 0, \
        "depth 2: emit worker absorbed no work"
    log(f"phase 2 OK: dispatch_thread_s/offloaded_s split present "
        f"(depth 2: {stats2['dispatch_thread_s']}s thread / "
        f"{stats2['offloaded_s']}s offloaded)")


def phase3_bench_knob() -> None:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    caps = {}
    for depth in (1, 2):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
             "--pipeline-depth", str(depth)],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
        assert out.returncode == 0 and out.stdout.strip(), (
            f"bench --smoke --pipeline-depth {depth} failed "
            f"rc={out.returncode}:\n{out.stderr[-2000:]}")
        cap = json.loads(out.stdout.strip().splitlines()[-1])
        assert cap.get("pipeline_depth") == depth, cap.get("pipeline_depth")
        dtb = cap.get("dispatch_thread_block_s") or {}
        assert dtb.get("p50") is not None and dtb.get("p99") is not None, \
            f"depth {depth}: capture has no dispatch_thread_block_s: {dtb}"
        assert cap.get("config", {}).get("pipeline_depth") == depth
        assert cap.get("config_fingerprint"), "capture is unstamped"
        caps[depth] = cap
    assert (caps[1]["config_fingerprint"]
            != caps[2]["config_fingerprint"]), \
        "depth 1 and 2 captures share a fingerprint — benchdiff would " \
        "silently diff across the knob"
    log(f"phase 3 OK: bench --pipeline-depth stamps depth + "
        f"dispatch_thread_block_s (depth1 p50 "
        f"{caps[1]['dispatch_thread_block_s']['p50']}s, depth2 p50 "
        f"{caps[2]['dispatch_thread_block_s']['p50']}s)")


def main() -> int:
    t0 = time.monotonic()
    stats1, stats2 = phase1_identity()
    phase2_split(stats1, stats2)
    phase3_bench_knob()
    log(f"ALL PHASES OK in {time.monotonic() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
