"""CI autoscale smoke: the telemetry→topology loop closed end to end.

A REAL tpu_native backend boots a 1×1 elastic pool (tiny CPU preset,
real engine-host subprocesses), then a synthetic burst lights the SLO
burn monitor the pool heartbeat feeds to PoolAutoscaler
(engine/disagg/autoscale.py), and the smoke asserts the full round
trip:

  phase 1 (scale up): a burst of over-target TTFT observations drives
  the fast-window burn ≫ 1; within a few heartbeats the controller
  books a SPAWN decision (decision counter increments) and the backend
  actuates it — a second REAL prefill member (inline node + handoff
  link) joins the pool and reaches HEALTHY. Requests streamed across
  the transition must all complete: scaling UP sheds nothing.

  phase 2 (new member serves): with the pool at 2×1, fresh requests
  place onto the joined member (placement counter asserted) — the
  spawned capacity is capacity, not a spectator.

  phase 3 (scale down): the load stops, the burn window empties, and
  after the idle-streak hysteresis the controller books a DRAIN; the
  idle member drains (zero in-flight sheds — drain-before-kill) and is
  retired back to 1×1, its chip-seconds banked in the pool ledger.

Zero failed client requests across all phases, and every decision is
visible in the pool stats' autoscale block.

Exit 0 on success; exit 1 with a reason otherwise.

Run: python tools/autoscale_smoke.py
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

# CPU pinning + shared compile cache BEFORE any jax import (the engine
# hosts inherit this environment; the warm cache is what makes the
# mid-run member spawn affordable).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/symmetry-tpu-disagg-smoke-cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def provider_config_dict() -> dict:
    return {
        "name": "autoscale-smoke-prov", "public": False,
        "serverKey": "00" * 32,
        "modelName": "tiny:autoscale", "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "flightRecorder": {"enabled": False},
        "tpu": {
            "model_preset": "tiny", "dtype": "float32",
            "max_batch_size": 4, "max_seq_len": 128,
            "prefill_buckets": [32, 64], "prefill_chunk": 16,
            "role": "disagg",
            "supervisor": {"heartbeat_s": 30.0, "wedge_timeout_s": 10.0,
                           "backoff_base_s": 0.2, "backoff_max_s": 1.0,
                           "max_respawns": 3, "spawn_timeout_s": 300.0,
                           "stop_grace_s": 5.0, "min_stable_s": 0.5},
            # Smoke-speed hysteresis: dwell and the idle streak are
            # heartbeats, not minutes; the churn cooldown stays long —
            # no churn is expected, and tripping it would be a bug.
            # drain_ticks 25 × 0.2s heartbeat = 5s of genuine idle
            # before the scale-down — enough for phases 1–2 to assert
            # against the joined member without racing the drain.
            "autoscale": {"max_members": 2, "dwell_s": 0.5,
                          "churn_cooldown_s": 60.0,
                          "drain_load": 0.25, "drain_ticks": 25},
            "disagg": {"peer": "mem://autoscale-smoke",
                       "reconnect_base_s": 0.05,
                       "pool": {"prefill": 1, "decode": 1,
                                "heartbeat_s": 0.2}},
        },
    }


async def run_smoke() -> int:
    from symmetry_tpu.provider.backends.base import (
        BackendRestartingError, InferenceRequest)
    from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.utils.metrics import SloMonitor

    async def collect(backend, content: str) -> str:
        text = []
        for _ in range(40):  # retry through any respawn window
            try:
                async for chunk in backend.stream(InferenceRequest(
                        messages=[{"role": "user", "content": content}],
                        max_tokens=8, temperature=0.0)):
                    if chunk.text:
                        text.append(chunk.text)
                break
            except BackendRestartingError as exc:
                await asyncio.sleep(exc.retry_after_s or 0.25)
        else:
            raise AssertionError(f"request never completed: {content!r}")
        return "".join(text)

    async def pool_autoscale(backend) -> tuple[dict, dict]:
        stats = await backend.engine_stats()
        pool = (stats.get("disagg") or {}).get("pool") or {}
        return pool, pool.get("autoscale") or {}

    backend = TpuNativeBackend(ConfigManager(config=provider_config_dict()))
    failures = 0
    try:
        await backend.start()
        # The provider's SLO burn monitor, attached exactly as
        # provider.py does; the pool heartbeat hands its per-SLO burns
        # to the controller every tick.
        monitor = SloMonitor({"ttft_s": 0.01, "objective": 0.9,
                              "fast_window_s": 4.0})
        backend.attach_slo_monitor(monitor)

        pool, asc = await pool_autoscale(backend)
        assert pool.get("healthy") == {"prefill": 1, "decode": 1}, \
            f"pool did not boot 1x1: {pool.get('healthy')}"
        assert asc, "autoscale block missing from pool stats"
        members_before = set(pool.get("members") or {})

        # phase 1: synthetic burst — a spike of over-target TTFTs.
        # Requests keep streaming across the scale-up the whole time.
        for _ in range(12):
            monitor.observe("ttft", 0.5)
        inflight = [asyncio.ensure_future(
            collect(backend, f"burst request {i} rides the spike"))
            for i in range(3)]
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if backend._pool.healthy_count("prefill") == 2:
                break
            await asyncio.sleep(0.1)
        pool, asc = await pool_autoscale(backend)
        assert backend._pool.healthy_count("prefill") == 2, \
            f"burst never scaled the pool to 2x1: {pool}"
        assert asc.get("spawns", 0) >= 1, f"no spawn decision: {asc}"
        assert any(d.get("action") == "spawn"
                   for d in asc.get("actions", [])), \
            f"spawn missing from the action log: {asc.get('actions')}"
        joined = set(pool.get("members") or {}) - members_before
        assert len(joined) == 1, f"expected one joined member: {joined}"
        new_member = joined.pop()
        texts = await asyncio.gather(*inflight)
        assert all(texts), "a burst request streamed no text"
        print(f"autoscale smoke: phase 1 burn spike → spawn decision → "
              f"{new_member} joined (2x1); {len(texts)} requests "
              f"streamed across the scale-up")

        # phase 2: the joined member takes placements — least-loaded
        # routing sends fresh work its way. The burn stays lit so the
        # idle streak cannot start under the asserts.
        for i in range(4):
            monitor.observe("ttft", 0.5)
            await collect(backend, f"post-scale request {i} lands wide")
        pool, asc = await pool_autoscale(backend)
        placed = (pool.get("members", {}).get(new_member) or {}
                  ).get("placements", 0)
        assert placed >= 1, \
            f"joined member {new_member} never served: {pool}"
        print(f"autoscale smoke: phase 2 {new_member} took {placed} "
              f"placement(s) at 2x1")

        # phase 3: load stops → burn window empties → idle streak →
        # DRAIN decision → drain-before-kill retire back to 1x1.
        # Poll the retire, not the drain: DRAINING drops the healthy
        # count immediately, but the drain-before-kill teardown takes
        # another beat to bank the member into the ledger.
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            pool, asc = await pool_autoscale(backend)
            if (pool.get("retires", 0) >= 1
                    and backend._pool.healthy_count("prefill") == 1):
                break
            await asyncio.sleep(0.2)
        assert backend._pool.healthy_count("prefill") == 1, \
            f"idle pool never drained back to 1x1: {pool}"
        assert asc.get("drains", 0) >= 1, f"no drain decision: {asc}"
        assert pool.get("retires", 0) >= 1, \
            f"drained member was not retired: {pool}"
        assert pool.get("re_placements", 0) == 0, \
            f"scaling shed in-flight work: {pool}"
        assert pool.get("chip_seconds", 0) > 0
        # The retired member still serves the ledger: its alive time
        # stays banked in the pool's chip-second total.
        final = await collect(backend, "the pool is 1x1 again")
        assert final, "post-drain request streamed no text"
        print(f"autoscale smoke: phase 3 idle drain → retired back to "
              f"1x1 (chip-seconds {pool.get('chip_seconds')}, "
              f"0 re-placements, 0 failed requests)")
    finally:
        try:
            await backend.stop()
        except Exception as exc:  # noqa: BLE001 — teardown must not mask
            print(f"autoscale smoke: teardown error: {exc!r}",
                  file=sys.stderr)
            failures += 1
    return 1 if failures else 0


def main() -> int:
    try:
        return asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(run_smoke(), timeout=600))
    except AssertionError as exc:
        print(f"autoscale smoke FAILED: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
