#!/usr/bin/env python3
"""benchdiff — machine-checkable verdicts over bench.py captures.

Ten PRs of levers produced BENCH_r*.json files that were compared by
eyeballing JSON diffs in prose. This tool replaces that: it compares
two bench captures (or a series) metric by metric with per-metric
DIRECTION, minimum-effect thresholds, and noise bands, emits a markdown
delta table, and exits nonzero on regression — so the r06+ campaign and
every future PR produce comparisons a CI step can gate on.

    python tools/benchdiff.py BASE.json NEW.json [--out delta.md]
    python tools/benchdiff.py r1.json r2.json r3.json NEW.json
    python tools/benchdiff.py BASE.json NEW.json --json

Series mode (3+ files): the LAST file is the candidate; the earlier
files are repeated runs of the baseline point, and the per-metric IQR
across them becomes the noise band — the empirical answer to "is this
delta real or is this metric just loud" (the bands a single pair can
only assume, repeated smoke runs measure).

Config-fingerprint guard: bench.py stamps every capture with a
`config_fingerprint` over its RESOLVED knobs (mode, slots, clients,
buckets, quantization, …). Captures whose fingerprints disagree are
refused LOUDLY (exit 2, differing knobs listed) instead of producing a
garbage delta — a tok/s drop between a 128-slot run and a 96-slot run
is a config diff wearing a regression costume. `--force` overrides for
deliberate cross-config comparisons (e.g. a knob A/B, where the knob
ITSELF is the diff) and prints the config delta beside the table.

Verdict policy (per metric, matched on the metric's path):

  - direction: `higher` (throughput) or `lower` (latency) — only
    policied metrics can REGRESS; every other shared numeric leaf is
    reported as `info` (counters and totals scale with workload size,
    so a naive "it changed" check would cry wolf on every run).
  - min_effect: the minimum RELATIVE change worth calling real (looser
    for latency percentiles than throughput — they are noisier).
  - noise band: max(min_effect × |base|, IQR across the baseline
    series when one was given). A worse-direction delta beyond the
    band is `REGRESSED` (exit 1); a better-direction delta beyond it
    is `improved`; inside the band is `ok`.

Exit codes: 0 = no regression, 1 = regression(s), 2 = refused
(fingerprint mismatch, missing/unreadable file, unstamped capture).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any

# (pattern over the dotted metric path, direction, min relative effect).
# First match wins; unmatched numeric leaves are informational.
POLICIES: list[tuple[re.Pattern, str, float]] = [
    (re.compile(r"(^|\.)value$"), "higher", 0.03),
    (re.compile(r"(^|\.)vs_baseline$"), "higher", 0.03),
    (re.compile(r"steady_state_tok_s$"), "higher", 0.03),
    (re.compile(r"per_slot_tok_s$"), "higher", 0.03),
    (re.compile(r"tok_s_(plain|speculative)$"), "higher", 0.05),
    (re.compile(r"(^|\.)speedup$"), "higher", 0.05),
    # Multi-turn cache-affinity payoff: turn-1 TTFT (cold prefill) over
    # turn-2+ TTFT (session lands on a member holding its radix
    # prefix). The pool-routing headline — a regression here means
    # follow-up turns stopped finding their cache.
    (re.compile(r"turn2plus_speedup$"), "higher", 0.05),
    # Autoscaler headline: SLO-attaining tokens per chip-second. A
    # regression means the controller is buying the same goodput with
    # more chips (or shedding goodput to save them).
    (re.compile(r"goodput_tokens_per_chip_s$"), "higher", 0.05),
    # symledger rollup (bench.py `ledger` block): attributed device
    # seconds per request and the wasted share are costs (lower); the
    # true-goodput headline — tokens per attributed device second —
    # must not fall.
    (re.compile(r"goodput_tokens_per_device_s$"), "higher", 0.05),
    (re.compile(r"ledger\.device_s_p\d+$"), "lower", 0.10),
    (re.compile(r"ledger\.wasted_share$"), "lower", 0.15),
    (re.compile(r"weight_stream_gbs$"), "higher", 0.05),
    (re.compile(r"acceptance_rate$"), "higher", 0.10),
    (re.compile(r"ttft[a-z0-9_]*_p\d+(_[a-z]+)?_s$"), "lower", 0.10),
    (re.compile(r"(^|\.)(mean_)?ttft_s$"), "lower", 0.10),
    (re.compile(r"e2e_p\d+_s$"), "lower", 0.10),
    (re.compile(r"inter_chunk_gap_p\d+_s$"), "lower", 0.15),
    (re.compile(r"decode_step_ms$"), "lower", 0.05),
    (re.compile(r"prefill_s_per_slot$"), "lower", 0.10),
    (re.compile(r"gap_share$"), "lower", 0.15),
    # Dispatch-thread wall per scheduler iteration (the pipelined-
    # scheduler target metric): host time the dispatch thread spends
    # per block after emit/bookkeep moved off-thread. Noisy like any
    # host-side latency — same band as the gap share it pairs with.
    (re.compile(r"dispatch_thread_block_s\.(p50|p99)$"), "lower", 0.15),
    (re.compile(r"recovery_[a-z0-9_]*s$"), "lower", 0.15),
    (re.compile(r"wasted_tokens$"), "lower", 0.15),
]

# Stamp/bookkeeping keys excluded from metric flattening.
_META_KEYS = frozenset((
    "schema", "git_sha", "written_at", "config", "config_fingerprint",
    "metric", "unit", "metrics"))


def flatten(obj: Any, prefix: str = "") -> dict[str, float]:
    """Numeric leaves of a capture as {dotted.path: value}. Lists are
    skipped (histogram buckets/recent rings are not comparison
    targets); bools are not numbers."""
    out: dict[str, float] = {}
    if not isinstance(obj, dict):
        return out
    for key, val in obj.items():
        if not prefix and key in _META_KEYS:
            continue
        path = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            out[path] = float(val)
        elif isinstance(val, dict):
            out.update(flatten(val, path))
    return out


def policy_for(path: str) -> tuple[str, float] | None:
    for pat, direction, min_effect in POLICIES:
        if pat.search(path):
            return direction, min_effect
    return None


def _median(xs: list[float]) -> float:
    ys = sorted(xs)
    n = len(ys)
    mid = n // 2
    return ys[mid] if n % 2 else (ys[mid - 1] + ys[mid]) / 2.0


def _iqr(xs: list[float]) -> float:
    """Interquartile range (nearest-rank quartiles) — the robust spread
    estimate the noise bands ride; 0 for < 3 samples (no basis)."""
    if len(xs) < 3:
        return 0.0
    ys = sorted(xs)
    q1 = ys[max(0, (len(ys) + 1) // 4 - 1)]
    q3 = ys[min(len(ys) - 1, (3 * (len(ys) + 1)) // 4 - 1)]
    return max(0.0, q3 - q1)


def compare(baselines: list[dict], candidate: dict,
            min_effect_override: float | None = None) -> list[dict]:
    """Per-metric rows over the candidate vs the baseline series (last
    baseline = the reference point for deltas; the whole series feeds
    the IQR noise band). Rows: {metric, base, new, delta, delta_pct,
    band, direction, verdict}."""
    base_flat = [flatten(b) for b in baselines]
    cand_flat = flatten(candidate)
    ref = base_flat[-1]
    rows: list[dict] = []
    for path in sorted(set(ref) & set(cand_flat)):
        base_v, new_v = ref[path], cand_flat[path]
        series = [f[path] for f in base_flat if path in f]
        pol = policy_for(path)
        delta = new_v - base_v
        delta_pct = (delta / abs(base_v)) if base_v else None
        row = {"metric": path, "base": base_v, "new": new_v,
               "delta": delta, "delta_pct": delta_pct}
        if pol is None:
            row.update(direction=None, band=None, verdict="info")
            rows.append(row)
            continue
        direction, min_effect = pol
        if min_effect_override is not None:
            min_effect = min_effect_override
        # With a series, deltas anchor on the MEDIAN baseline (one
        # outlier run must not decide the reference); the printed
        # base/Δ columns still show the last baseline for readability.
        ref_point = _median(series) if len(series) >= 3 else base_v
        band = max(min_effect * abs(ref_point), _iqr(series))
        anchored = new_v - ref_point
        worse = anchored < 0 if direction == "higher" else anchored > 0
        if abs(anchored) <= band:
            verdict = "ok"
        elif worse:
            verdict = "REGRESSED"
        else:
            verdict = "improved"
        row.update(direction=direction, band=band, verdict=verdict)
        rows.append(row)
    # Policied rows first (verdicts are the point), regressions on top.
    order = {"REGRESSED": 0, "improved": 1, "ok": 2, "info": 3}
    rows.sort(key=lambda r: (order[r["verdict"]], r["metric"]))
    return rows


def _fmt(v: float | None) -> str:
    if v is None:
        return "-"
    if v == int(v) and abs(v) < 1e12:
        return str(int(v))
    return f"{v:.4g}"


def render_markdown(rows: list[dict], baselines: list[dict],
                    candidate: dict, forced_mismatch: list[str]) -> str:
    """The delta table a PR description (or a CI log) can paste."""
    base, cand = baselines[-1], candidate
    lines = ["# benchdiff", ""]
    lines.append(f"- baseline: `{base.get('config', {}).get('mode', '?')}`"
                 f" @ `{(base.get('git_sha') or 'unknown')[:12]}`"
                 + (f" (series of {len(baselines)}, IQR noise bands)"
                    if len(baselines) > 1 else ""))
    lines.append(f"- candidate: `{cand.get('config', {}).get('mode', '?')}`"
                 f" @ `{(cand.get('git_sha') or 'unknown')[:12]}`")
    if forced_mismatch:
        lines.append("- **forced cross-config comparison** — differing "
                     "knobs: " + ", ".join(
                         f"`{k}`" for k in forced_mismatch))
    n_reg = sum(1 for r in rows if r["verdict"] == "REGRESSED")
    n_imp = sum(1 for r in rows if r["verdict"] == "improved")
    lines.append(f"- verdict: "
                 + ("**REGRESSED**" if n_reg else "ok")
                 + f" ({n_reg} regressed, {n_imp} improved, "
                 f"{sum(1 for r in rows if r['verdict'] == 'ok')} within "
                 f"noise)")
    lines += ["", "| metric | base | new | Δ | Δ% | band | verdict |",
              "|---|---|---|---|---|---|---|"]
    for r in rows:
        pct = (f"{100 * r['delta_pct']:+.1f}%"
               if r["delta_pct"] is not None else "-")
        verdict = (f"**{r['verdict']}**" if r["verdict"] == "REGRESSED"
                   else r["verdict"])
        lines.append(
            f"| `{r['metric']}` | {_fmt(r['base'])} | {_fmt(r['new'])} "
            f"| {_fmt(r['delta'])} | {pct} | {_fmt(r['band'])} "
            f"| {verdict} |")
    return "\n".join(lines) + "\n"


def _load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a bench capture (not an object)")
    return data


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchdiff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("captures", nargs="+", metavar="JSON",
                    help="bench.py captures; the LAST is the candidate, "
                         "everything before it the baseline series")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the markdown delta table here")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit rows as JSON instead of markdown")
    ap.add_argument("--force", action="store_true",
                    help="compare despite fingerprint mismatch / missing "
                         "stamps (deliberate knob A/Bs)")
    ap.add_argument("--min-effect", type=float, default=None,
                    metavar="FRAC",
                    help="override every policy's minimum relative "
                         "effect (e.g. 0.05)")
    args = ap.parse_args(argv)
    if len(args.captures) < 2:
        print("benchdiff: need at least a baseline and a candidate",
              file=sys.stderr)
        return 2
    try:
        captures = [_load(p) for p in args.captures]
    except (OSError, ValueError) as exc:
        print(f"benchdiff: {exc}", file=sys.stderr)
        return 2
    baselines, candidate = captures[:-1], captures[-1]

    # ---- config-fingerprint guard (the loud refusal) ------------------
    forced_mismatch: list[str] = []
    stamps = [c.get("config_fingerprint") for c in captures]
    if any(s is None for s in stamps):
        which = [p for p, s in zip(args.captures, stamps) if s is None]
        msg = ("unstamped capture(s) (no config_fingerprint — pre-schema "
               "bench JSON?): " + ", ".join(which))
        if not args.force:
            print(f"benchdiff: REFUSING comparison — {msg}\n"
                  f"  rerun bench.py to produce stamped captures, or pass "
                  f"--force to compare anyway", file=sys.stderr)
            return 2
        print(f"benchdiff: WARNING — {msg} (forced)", file=sys.stderr)
    elif len(set(stamps)) > 1:
        # Differing knobs across the WHOLE set (a middle series file
        # can be the odd one out — diagnostics must name it, not just
        # diff endpoint configs that happen to agree).
        configs = [c.get("config") or {} for c in captures]
        all_keys = set().union(*configs)
        forced_mismatch = sorted(
            k for k in all_keys
            if len({json.dumps(cfg.get(k), sort_keys=True)
                    for cfg in configs}) > 1)
        if not args.force:
            print("benchdiff: REFUSING comparison — config fingerprints "
                  "disagree; a delta across different configs is a "
                  "config diff, not a regression.\n  differing knobs:",
                  file=sys.stderr)
            for k in forced_mismatch:
                vals = " / ".join(
                    f"{os.path.basename(p)}={cfg.get(k)!r}"
                    for p, cfg in zip(args.captures, configs))
                print(f"    {k}: {vals}", file=sys.stderr)
            print("  pass --force for a deliberate cross-config A/B",
                  file=sys.stderr)
            return 2
        knobs = ", ".join(forced_mismatch) or "<fingerprint only>"
        print("benchdiff: WARNING — cross-config comparison forced "
              f"(differing: {knobs})", file=sys.stderr)

    rows = compare(baselines, candidate,
                   min_effect_override=args.min_effect)
    regressed = [r for r in rows if r["verdict"] == "REGRESSED"]
    if args.as_json:
        print(json.dumps({
            "schema": 1,
            "regressed": bool(regressed),
            "baseline_sha": baselines[-1].get("git_sha"),
            "candidate_sha": candidate.get("git_sha"),
            "forced_mismatch": forced_mismatch,
            "rows": rows}, indent=1))
    md = render_markdown(rows, baselines, candidate, forced_mismatch)
    if not args.as_json:
        print(md, end="")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(md)
        print(f"[benchdiff] delta table → {args.out}", file=sys.stderr)
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
