"""CI metrics smoke: scrape a serving provider mid-request, end to end.

Spins up the full client → server → provider path on the in-memory
transport with an echo backend (no TPU, no subprocess), with the
telemetry layer in its production shape: the Prometheus exposition
endpoint on an ephemeral port, the SLO burn-rate monitor armed with an
impossible TTFT target, and the flight recorder wired to it. Then:

  1. starts a streamed chat and scrapes /metrics WHILE it is in
     flight: >= 10 `sym_` metric families must parse, with sane
     mid-request values (in_flight >= 1, requests_total >= 1);
  2. finishes the chat, scrapes again: tokens flowed, TTFT histogram
     filled, uptime advanced;
  3. asserts the SLO monitor burned (every request misses the
     impossible target) — breach counter up AND the flight recorder
     dumped a `slo_burn_ttft` artifact;
  4. fetches the same snapshots over the peer wire (the swarm path:
     MessageKey.METRICS reply carries the registry snapshots — no
     open port needed) and cross-checks them against the scrape;
  5. renders the fleet table via `symtop --once --metrics-url ...` and
     asserts the provider row shows real numbers;
  6. asserts the disabled-mode overhead contract: with the registry
     disabled, instrumented call sites cost one branch — 200k guarded
     ops under 0.5 s, and per-op cost x a whole chunk's call count
     under 1% of a 1 ms chunk budget (the echo-path overhead bound).

Exit 0 on success; exit 1 with a reason otherwise.

Run: python tools/metrics_smoke.py
"""

from __future__ import annotations

import asyncio
import io
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def run(tmp_dir: str) -> int:
    import contextlib

    from symmetry_tpu.client.client import SymmetryClient
    from symmetry_tpu.identity import Identity
    from symmetry_tpu.provider.backends.echo import EchoBackend
    from symmetry_tpu.provider.config import ConfigManager
    from symmetry_tpu.provider.provider import SymmetryProvider
    from symmetry_tpu.server.broker import SymmetryServer
    from symmetry_tpu.transport.memory import MemoryTransport
    from symmetry_tpu.utils.metrics import METRICS, parse_prometheus_text

    hub = MemoryTransport()
    server_ident = Identity.from_name("metrics-smoke-server")
    server = SymmetryServer(server_ident, hub, ping_interval_s=30.0)
    await server.start("mem://metrics-server")

    flight_dir = os.path.join(tmp_dir, "flight")
    cfg = ConfigManager(config={
        "name": "metrics-smoke-prov",
        "public": True,
        "serverKey": server_ident.public_hex,
        "modelName": "echo:metrics",
        "apiProvider": "echo",
        "dataCollectionEnabled": False,
        "metrics": {"port": 0},          # ephemeral exposition endpoint
        "flightRecorder": {"enabled": True, "dir": flight_dir,
                           "minIntervalS": 0.0},
        # Impossible TTFT target: every request burns the budget, so the
        # breach → flight-dump chain is exercised deterministically.
        "slo": {"ttft_s": 1e-4, "objective": 0.99, "fast_window_s": 60.0,
                "slow_window_s": 600.0, "burn_threshold": 5.0,
                "min_samples": 1, "min_interval_s": 0.0},
    })
    provider = SymmetryProvider(
        cfg, transport=hub,
        identity=Identity.from_name("metrics-smoke-prov"),
        backend=EchoBackend(delay_s=0.03),
        server_address="mem://metrics-server")
    await provider.start("mem://metrics-smoke-prov")
    await provider.wait_registered()
    assert provider.metrics_server is not None, "metrics endpoint not up"
    url = f"http://127.0.0.1:{provider.metrics_server.port}/metrics"

    def _scrape_blocking() -> dict:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return parse_prometheus_text(resp.read().decode())

    async def scrape() -> dict:
        # Off-loop on purpose: the exposition handler bridges INTO this
        # event loop (the host-probe path), so a scrape blocking the
        # loop would deadlock itself — exactly what a real Prometheus
        # (its own process) never does.
        return await asyncio.to_thread(_scrape_blocking)

    client = SymmetryClient(Identity.from_name("metrics-smoke-cli"), hub)
    details = await client.request_provider(
        "mem://metrics-server", server_ident.public_key, "echo:metrics")
    session = await client.connect(details)
    try:
        # ---- 1: scrape MID-REQUEST ------------------------------------
        prompt = " ".join(f"w{i}" for i in range(40))  # ~1.2 s stream

        async def chat() -> str:
            return "".join([d async for d in session.chat(
                [{"role": "user", "content": prompt}])])

        task = asyncio.ensure_future(chat())
        await asyncio.sleep(0.4)  # well inside the stream
        assert not task.done(), "stream finished before the scrape"
        fams = await scrape()
        sym = {n for n in fams if n.startswith("sym_")}
        print(f"metrics smoke: {len(sym)} sym_ families mid-request: "
              f"{sorted(sym)}")
        assert len(sym) >= 10, f"only {len(sym)} families: {sorted(sym)}"

        def val(fams: dict, name: str, suffix: str = "") -> float:
            fam = fams.get(name) or {"series": []}
            return sum(s["value"] for s in fam["series"]
                       if s.get("suffix", "") == suffix)

        assert val(fams, "sym_provider_in_flight") >= 1, \
            "in_flight must be >= 1 mid-request"
        assert val(fams, "sym_provider_requests_total") >= 1
        assert val(fams, "sym_provider_connections") >= 1

        # ---- 2: finish, scrape again ----------------------------------
        text = await task
        assert text == prompt, f"echo mismatch: {text[:60]!r}"
        fams = await scrape()
        assert val(fams, "sym_provider_tokens_out_total") >= 40
        assert val(fams, "sym_provider_ttft_seconds", "_count") >= 1
        assert val(fams, "sym_provider_inter_chunk_seconds", "_count") >= 10
        assert val(fams, "sym_provider_uptime_seconds") > 0
        assert val(fams, "sym_provider_in_flight") == 0

        # ---- 3: SLO burn → breach counter + flight-recorder dump ------
        assert val(fams, "sym_slo_breaches_total") >= 1, \
            "impossible TTFT target did not burn the SLO"
        await asyncio.sleep(0.3)  # the dump task is spawned, let it land
        dumps = [f for f in os.listdir(flight_dir)
                 if "slo_burn_ttft" in f] if os.path.isdir(flight_dir) \
            else []
        assert dumps, "SLO burn produced no flight-recorder dump"
        print(f"metrics smoke: SLO burn dumped {dumps[0]}")

        # ---- 4: the swarm path (wire metrics block) -------------------
        stats = await session.stats()
        snaps = (stats.get("metrics") or {}).get("snapshots")
        assert snaps, "METRICS reply carries no registry snapshots"
        wire_fams = snaps[0]["snapshot"]["families"]
        assert "sym_provider_tokens_out_total" in wire_fams
        wire_tok = sum(s["value"] for s in
                       wire_fams["sym_provider_tokens_out_total"]["series"])
        assert wire_tok == val(fams, "sym_provider_tokens_out_total"), \
            "wire snapshot disagrees with the HTTP scrape"
    finally:
        await session.close()

    # ---- 5: symtop --once renders the fleet table ---------------------
    import tools.symtop as symtop

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        # Off-loop like the scrapes: symtop's HTTP poll must not block
        # the event loop its target renders on.
        rc = await asyncio.to_thread(
            symtop.main, ["--once", "--metrics-url", url])
    table = buf.getvalue()
    print("metrics smoke: symtop table:\n" + table)
    assert rc == 0, "symtop --once failed"
    assert "PROVIDER" in table and "TTFT p50" in table
    row = table.splitlines()[1]
    assert "127.0.0.1" in row and "ERR" not in row
    tok_cell = row.split()[2]  # PROVIDER, TIER, TOK/S
    assert float(tok_cell) > 0, f"provider row shows no tok/s: {row!r}"

    await provider.stop()
    await server.stop()

    # ---- 6: disabled-mode overhead contract ---------------------------
    METRICS.enabled = False
    try:
        c = METRICS.counter("sym_provider_requests_total")
        t0 = time.perf_counter()
        for _ in range(200_000):
            c.inc()
        dt = time.perf_counter() - t0
    finally:
        METRICS.enabled = True
    per_op = dt / 200_000
    print(f"metrics smoke: disabled per-op {per_op * 1e9:.0f} ns "
          f"({dt:.3f}s / 200k)")
    assert dt < 0.5, f"disabled-mode overhead {dt:.3f}s for 200k ops"
    # Echo-path bound: a streamed chunk touches a handful of metric
    # sites; even 5 of them must cost under 1% of a 1 ms chunk budget.
    assert per_op * 5 < 0.01 * 1e-3, \
        f"disabled per-op {per_op * 1e9:.0f} ns breaks the 1% echo bound"
    return 0


def main() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="metrics_smoke_") as tmp:
        try:
            return asyncio.new_event_loop().run_until_complete(
                asyncio.wait_for(run(tmp), 120))
        except AssertionError as exc:
            print(f"metrics smoke FAILED: {exc}", file=sys.stderr)
            return 1


if __name__ == "__main__":
    sys.exit(main())
