"""Build distributables: wheel + single-file zipapp (round-3 verdict #9).

The reference ships an npm global install plus a `pkg` single-binary
build (reference: package.json:8-16, install.sh:21-27). The Python-era
equivalents here:

  dist/symmetry_tpu-<ver>-py3-none-any.whl   pip/pipx-installable wheel
                                             (console scripts: provider,
                                             server, client)
  dist/symmetry-tpu.pyz                      single-FILE app: run any role
                                             with `python symmetry-tpu.pyz
                                             {provider|server|client} ...`
                                             on any machine whose Python
                                             env has the deps (jax etc. —
                                             the TPU runtime cannot be
                                             bundled into an archive, so
                                             unlike `pkg` the interpreter
                                             + deps come from the host)

Run: python tools/build_dist.py   (writes ./dist; no network needed)
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import zipapp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIST = os.path.join(REPO, "dist")

ZIPAPP_MAIN = '''\
"""Single-file entry: symmetry-tpu.pyz {provider|server|client} [args...]"""
import runpy
import sys

ROLES = ("provider", "server", "client")
if len(sys.argv) < 2 or sys.argv[1] not in ROLES:
    print(f"usage: {sys.argv[0]} {{{'|'.join(ROLES)}}} [args...]",
          file=sys.stderr)
    sys.exit(2)
role = sys.argv.pop(1)
runpy.run_module(f"symmetry_tpu.{role}", run_name="__main__")
'''


def build_wheel() -> str:
    """Pure-python wheel via pip (offline: no deps resolved)."""
    try:
        subprocess.run(
            [sys.executable, "-m", "pip", "wheel", "--no-deps",
             "--no-build-isolation", "-w", DIST, REPO],
            check=True, cwd=REPO)
    finally:
        # setuptools litters the source tree; keep the checkout clean
        shutil.rmtree(os.path.join(REPO, "build"), ignore_errors=True)
        shutil.rmtree(os.path.join(REPO, "symmetry_tpu.egg-info"),
                      ignore_errors=True)
    wheels = sorted(f for f in os.listdir(DIST) if f.endswith(".whl"))
    assert wheels, "no wheel produced"
    return os.path.join(DIST, wheels[-1])


def build_zipapp() -> str:
    staging = tempfile.mkdtemp(prefix="symmetry_zipapp_")
    try:
        shutil.copytree(
            os.path.join(REPO, "symmetry_tpu"),
            os.path.join(staging, "symmetry_tpu"),
            ignore=shutil.ignore_patterns("__pycache__", "*.pyc"))
        with open(os.path.join(staging, "__main__.py"), "w") as fh:
            fh.write(ZIPAPP_MAIN)
        out = os.path.join(DIST, "symmetry-tpu.pyz")
        zipapp.create_archive(staging, out,
                              interpreter="/usr/bin/env python3")
        return out
    finally:
        shutil.rmtree(staging, ignore_errors=True)


def main() -> None:
    os.makedirs(DIST, exist_ok=True)
    wheel = build_wheel()
    pyz = build_zipapp()
    print(f"wheel:  {wheel}")
    print(f"zipapp: {pyz}")
    print("install:  pipx install " + os.path.basename(wheel)
          + "   (or pip install)")
    print("run:      python symmetry-tpu.pyz provider -c provider.yaml")


if __name__ == "__main__":
    main()
