"""Measure asyncio loop starvation while the engine thread drives the TPU.

Serving symptom (bench --e2e): every client's TTFT ≈ wall time — token
events flush only when the engine goes idle. Hypothesis: the engine
thread's JAX calls (dispatch / np.asarray sync over the axon tunnel) hold
the GIL, starving the provider's event loop.

This runs a 10 ms asyncio ticker while the engine thread executes decode
blocks, and prints the largest loop stalls per phase plus where in the
engine call they occur.

Run: python tools/probe_loop_starvation.py [--preset llama3.2-1b]
"""

from __future__ import annotations

import argparse
import asyncio
import threading
import time


async def ticker(gaps: list, stop: threading.Event) -> None:
    last = time.perf_counter()
    while not stop.is_set():
        await asyncio.sleep(0.01)
        now = time.perf_counter()
        gaps.append(now - last)
        last = now


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama3.2-1b")
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--block", type=int, default=16)
    args = ap.parse_args()

    import jax.numpy as jnp

    from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
    from symmetry_tpu.engine.tokenizer import ByteTokenizer
    from symmetry_tpu.models import init_params, preset

    cfg = preset(args.preset)
    params = init_params(cfg, __import__("jax").random.key(0), jnp.bfloat16,
                         quantize=True)
    engine = InferenceEngine(
        cfg, params, ByteTokenizer(), max_slots=args.slots, max_seq_len=256,
        prefill_buckets=(64,), cache_dtype=jnp.bfloat16,
        decode_block=args.block, kv_quant=True)
    engine.warmup()
    engine.prefill_and_insert(0, list(b"probe prompt"), SamplingParams())

    async def run() -> None:
        stop = threading.Event()
        phases: dict[str, list] = {}

        def engine_work() -> None:
            # phase 1: decode dispatch only (async)
            t0 = time.perf_counter()
            pending = []
            while time.perf_counter() - t0 < 3:
                pending.append(engine.decode_steps_dispatch())
            # phase 2: dispatch + sync (the serving loop's real shape)
            t0 = time.perf_counter()
            import numpy as np

            while time.perf_counter() - t0 < 5:
                np.asarray(engine.decode_steps_dispatch())
            stop.set()

        gaps: list = []
        phases["all"] = gaps
        thread = threading.Thread(target=engine_work, daemon=True)
        tick = asyncio.get_running_loop().create_task(ticker(gaps, stop))
        t_start = time.perf_counter()
        thread.start()
        await tick
        dur = time.perf_counter() - t_start
        gaps.sort(reverse=True)
        ticks = len(gaps)
        print(f"{dur:.1f}s, {ticks} ticks (expected ~{int(dur / 0.01)}), "
              f"worst loop stalls: "
              f"{[round(g, 3) for g in gaps[:8]]}", flush=True)

    asyncio.new_event_loop().run_until_complete(run())


if __name__ == "__main__":
    main()
