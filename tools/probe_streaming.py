"""Diagnose end-of-run event bunching in the serving path.

bench --e2e measures every client's TTFT ≈ wall time: token events reach
clients only when the run ends. This drives the REAL serving stack
(server + provider subprocess + tpu_native engine host) with a handful of
clients and prints each delta's arrival time per client, to localize
where streaming stalls (host → provider → wire → client).

Run: python tools/probe_streaming.py [--clients 4 --max-new 48]
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import os
import subprocess
import sys
import tempfile
import time

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from symmetry_tpu.client.client import SymmetryClient  # noqa: E402
from symmetry_tpu.identity import Identity  # noqa: E402
from symmetry_tpu.server.broker import SymmetryServer  # noqa: E402
from symmetry_tpu.transport.tcp import TcpTransport  # noqa: E402


async def main(args) -> None:
    server_ident = Identity.from_name("probe-server")
    server = SymmetryServer(server_ident, TcpTransport(),
                            ping_interval_s=60.0)
    await server.start("tcp://127.0.0.1:0")
    model = f"{args.preset}:probe"
    cfg = {
        "name": "probe-prov", "public": True,
        "serverKey": server_ident.public_hex,
        "serverAddress": server.address,
        "modelName": model, "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "maxConnections": args.clients + 4,
        "listenHost": "127.0.0.1",
        "privateSeed": hashlib.blake2b(b"probe-prov",
                                       digest_size=32).hexdigest(),
        "tpu": {"model_preset": args.preset, "dtype": "bfloat16",
                "quantization": "int8", "kv_quantization": "int8",
                "max_batch_size": args.slots, "max_seq_len": 384,
                "prefill_buckets": [128], "decode_block": args.block},
    }
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as fh:
        yaml.safe_dump(cfg, fh)
        cfg_path = fh.name
    proc = subprocess.Popen(
        [sys.executable, "-m", "symmetry_tpu.provider", "-c", cfg_path],
        cwd=REPO, stderr=subprocess.STDOUT,
        stdout=open("/tmp/probe_provider.log", "w"))
    t_reg0 = time.monotonic()
    while server.registry.select_provider(model) is None:
        if proc.poll() is not None:
            raise RuntimeError("provider died")
        await asyncio.sleep(0.5)
    print(f"provider ready after {time.monotonic() - t_reg0:.1f}s",
          flush=True)

    t0 = time.perf_counter()

    async def one(i: int) -> None:
        client = SymmetryClient(Identity.from_name(f"probe-cli-{i}"),
                                TcpTransport())
        details = await client.request_provider(
            server.address, server_ident.public_key, model)
        session = await client.connect(details)
        stamps = []
        async for delta in session.chat(
                [{"role": "user", "content": "y" * 90}],
                max_tokens=args.max_new, temperature=0.7, seed=i):
            stamps.append((round(time.perf_counter() - t0, 2), len(delta)))
        usage = session.last_usage
        await session.close()
        head = stamps[:6]
        tail = stamps[-2:] if len(stamps) > 8 else []
        print(f"client {i}: {len(stamps)} deltas, usage={usage}, "
              f"arrivals {head}…{tail}", flush=True)

    await asyncio.gather(*(one(i) for i in range(args.clients)))
    print(f"wall: {time.perf_counter() - t0:.2f}s", flush=True)
    proc.terminate()
    proc.wait(timeout=20)
    os.unlink(cfg_path)
    await server.stop()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama3.2-1b")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--block", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=48)
    asyncio.new_event_loop().run_until_complete(ap.parse_args() and main(
        ap.parse_args()))
