"""Piecewise timing of the decode step on the real chip.

Times each stage of the serving decode step in isolation (trunk, attention,
LM head, sampling, cache scatter) to locate the gap between the measured
step time and the HBM-bandwidth floor. Not part of the test suite; run
manually: `python tools/profile_decode.py [--preset llama3-8b ...]`.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3  # ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=128)
    ap.add_argument("--max-seq", type=int, default=640)
    args = ap.parse_args()

    from symmetry_tpu.models.llama import (
        forward_hidden, init_cache, init_params, logits_from_hidden, preset)
    from symmetry_tpu.ops.attention import gqa_attention
    from symmetry_tpu.ops.sampling import sample_tokens

    cfg = preset(args.preset)
    B, T = args.slots, args.max_seq
    params = init_params(cfg, jax.random.key(0), jnp.bfloat16, quantize=True)
    cache = init_cache(cfg, B, T, jnp.bfloat16, quantized=True)
    cache = cache._replace(lengths=jnp.full((B,), T - 8, jnp.int32))
    tok = jnp.ones((B, 1), jnp.int32)

    # Full trunk (all layers incl. attention + cache writes)
    trunk = jax.jit(lambda p, t, c: forward_hidden(p, cfg, t, c),
                donate_argnums=(2,))
    def trunk_once(p, t, c):
        out = trunk(p, t, c)
        return out  # new cache replaces donated one
    for _ in range(3):
        _, cache = trunk(params, tok, cache)
    import time as _t
    t0 = _t.perf_counter()
    for _ in range(20):
        h, cache = trunk(params, tok, cache)
    jax.block_until_ready(h)
    ms_trunk = (_t.perf_counter() - t0) / 20 * 1e3

    # LM head
    h = jnp.ones((B, 1, cfg.hidden_size), jnp.bfloat16)
    head = jax.jit(lambda p, h: logits_from_hidden(p, cfg, h))
    ms_head = timeit(head, params, h)

    # Sampling
    logits = jnp.ones((B, cfg.vocab_size), jnp.float32)
    keys = jax.random.split(jax.random.key(0), B)
    temp = jnp.full((B,), 0.7, jnp.float32)
    top_p = jnp.ones((B,), jnp.float32)
    top_k = jnp.zeros((B,), jnp.int32)
    samp = jax.jit(sample_tokens)
    ms_samp = timeit(samp, logits, keys, temp, top_p, top_k)

    # Attention alone, one layer, einsum path (what the trunk uses at T<4096)
    D, nq, nkv = cfg.dim_per_head, cfg.num_heads, cfg.num_kv_heads
    q = jnp.ones((B, 1, nq, D), jnp.bfloat16)
    k1 = cache.k[0]
    v1 = cache.v[0]
    ks = cache.k_scale[0]
    pos = jnp.full((B, 1), T - 8, jnp.int32)
    kl = jnp.full((B,), T - 7, jnp.int32)
    attn = jax.jit(lambda q, k, v, ks, vs: gqa_attention(
        q, k, v, pos, kl, k_scale=ks, v_scale=vs))
    ms_attn1 = timeit(attn, q, k1, v1, ks, ks)
    del k1, v1, ks

    # Cache scatter write, one layer-equivalent (full-cache .at[].set)
    kq = jnp.ones((B, 1, nkv, D), jnp.int8)
    lidx = jnp.zeros((B, 1), jnp.int32)
    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]

    def scatter(c, kq):
        return c.k.at[lidx, bidx, pos].set(kq)

    ms_scat1 = timeit(jax.jit(scatter), cache, kq)

    # Pallas ragged decode kernel at this capacity (if divisible)
    ms_pallas1 = float("nan")
    from symmetry_tpu.ops import decode_attention as da
    for bt in (512, 256, 128):
        if T % bt == 0 and bt <= T:
            q3 = jnp.ones((B, nq, D), jnp.bfloat16)
            pal = jax.jit(lambda q3, k, v, ks, vs: da.decode_attention(
                q3, cache.k, cache.v, jnp.int32(0), kl,
                k_scale=ks, v_scale=vs, block_t=bt))
            ms_pallas1 = timeit(pal, q3, cache.k, cache.v,
                                cache.k_scale, cache.v_scale)
            break

    L = cfg.num_layers
    print(f"trunk (all {L} layers):   {ms_trunk:8.2f} ms")
    print(f"lm head:                  {ms_head:8.2f} ms")
    print(f"sampling:                 {ms_samp:8.2f} ms")
    print(f"attention x1 (einsum):    {ms_attn1:8.2f} ms  (x{L} = {ms_attn1*L:.1f})")
    print(f"attention x1 (pallas):    {ms_pallas1:8.2f} ms  (x{L} = {ms_pallas1*L:.1f})")
    print(f"cache scatter x1 (k):     {ms_scat1:8.2f} ms  (x{2*L} = {ms_scat1*2*L:.1f})")
    print(f"sum trunk+head+sample:    {ms_trunk + ms_head + ms_samp:8.2f} ms")

    # bandwidth sanity: weight bytes + kv bytes
    wb = sum(np.prod(x.shape) * x.dtype.itemsize
             for x in jax.tree.leaves(params))
    kvb = (2 * L * B * T * nkv * D * 1
           + 2 * L * B * nkv * T * 4)
    print(f"weight bytes: {wb/1e9:.2f} GB  kv bytes: {kvb/1e9:.2f} GB")


if __name__ == "__main__":
    main()
