"""Piecewise timing of the decode step on the real chip.

Times each stage of the serving decode step in isolation (trunk, attention,
LM head, sampling, cache scatter) to locate the gap between the measured
step time and the HBM-bandwidth floor. Not part of the test suite; run
manually: `python tools/profile_decode.py [--preset llama3-8b ...]`.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_util import sync, timeit  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=128)
    ap.add_argument("--max-seq", type=int, default=640)
    ap.add_argument("--kv-quant", default="int8", choices=("none", "int8"),
                    help="A/B the cache dtype: if the int8 cache read were "
                         "upcast-materialized by XLA, int8 would not beat "
                         "bf16 here")
    ap.add_argument("--trunk-only", action="store_true")
    force = ap.add_mutually_exclusive_group()
    force.add_argument("--force-kernel", action="store_true",
                       help="route decode attention through the Pallas "
                            "ragged kernel regardless of capacity")
    force.add_argument("--force-einsum", action="store_true",
                       help="disable the Pallas decode kernel (A/B at "
                            "capacities where it is the default)")
    ap.add_argument("--occupancy", type=int, default=None,
                    help="per-slot cache occupancy for the trunk timing "
                         "(default: near capacity)")
    args = ap.parse_args()

    if args.force_kernel:
        from symmetry_tpu.ops import decode_attention as _da
        _da.MIN_CAPACITY = 0
    if args.force_einsum:
        from symmetry_tpu.ops import decode_attention as _da
        _da.MIN_CAPACITY = 10**9

    from symmetry_tpu.models.llama import (
        forward_hidden, init_cache, init_params, logits_from_hidden, preset)
    from symmetry_tpu.ops.attention import gqa_attention
    from symmetry_tpu.ops.sampling import sample_tokens

    cfg = preset(args.preset)
    B, T = args.slots, args.max_seq
    kvq = args.kv_quant == "int8"
    n_warm, n_timed = 3, 20
    params = init_params(cfg, jax.random.key(0), jnp.bfloat16, quantize=True)
    cache = init_cache(cfg, B, T, jnp.bfloat16, quantized=kvq)
    # Start far enough from capacity that every warmup+timed step writes in
    # bounds — out-of-bounds scatters are silently dropped under jit, which
    # would make the tail iterations measure different work.
    occ = (args.occupancy if args.occupancy is not None
           else T - (n_warm + n_timed + 1))
    occ = min(occ, T - (n_warm + n_timed + 1))
    cache = cache._replace(lengths=jnp.full((B,), occ, jnp.int32))
    tok = jnp.ones((B, 1), jnp.int32)

    # Full trunk (all layers incl. attention + cache writes)
    trunk = jax.jit(lambda p, t, c: forward_hidden(p, cfg, t, c),
                donate_argnums=(2,))
    for _ in range(n_warm):
        h, cache = trunk(params, tok, cache)
    sync(h)
    t0 = time.perf_counter()
    for _ in range(n_timed):
        h, cache = trunk(params, tok, cache)
    sync(h)
    ms_trunk = (time.perf_counter() - t0) / n_timed * 1e3

    L = cfg.num_layers
    print(f"trunk (all {L} layers):   {ms_trunk:8.2f} ms  "
          f"(B={B} T={T} occ={occ} kv={'int8' if kvq else 'bf16'}"
          f"{' kernel' if args.force_kernel else ''}"
          f"{' einsum' if args.force_einsum else ''})", flush=True)
    if args.trunk_only:
        return

    # LM head
    h = jnp.ones((B, 1, cfg.hidden_size), jnp.bfloat16)
    head = jax.jit(lambda p, h: logits_from_hidden(p, cfg, h))
    ms_head = timeit(head, params, h)
    print(f"lm head:                  {ms_head:8.2f} ms", flush=True)

    # Sampling
    logits = jnp.ones((B, cfg.vocab_size), jnp.float32)
    keys = jax.random.split(jax.random.key(0), B)
    temp = jnp.full((B,), 0.7, jnp.float32)
    top_p = jnp.ones((B,), jnp.float32)
    top_k = jnp.zeros((B,), jnp.int32)
    samp = jax.jit(sample_tokens)
    ms_samp = timeit(samp, logits, keys, temp, top_p, top_k)
    del logits, keys
    print(f"sampling:                 {ms_samp:8.2f} ms", flush=True)
    print(f"sum trunk+head+sample:    {ms_trunk + ms_head + ms_samp:8.2f} ms",
          flush=True)

    # Attention alone, one layer, einsum path (what the trunk uses at
    # T<4096). Positions/lengths passed as ARGUMENTS — closed-over device
    # arrays would be baked into the jaxpr as constants (host round-trip +
    # a device copy at trace time).
    D, nq, nkv = cfg.dim_per_head, cfg.num_heads, cfg.num_kv_heads
    q = jnp.ones((B, 1, nq, D), jnp.bfloat16)
    pos = jnp.full((B, 1), T - 8, jnp.int32)
    kl = jnp.full((B,), T - 7, jnp.int32)
    attn = jax.jit(lambda q, k, v, ks, vs, pos, kl: gqa_attention(
        q, k, v, pos, kl, k_scale=ks, v_scale=vs))
    try:
        ms_attn1 = timeit(attn, q, cache.k[0], cache.v[0], cache.k_scale[0],
                          cache.v_scale[0], pos, kl)
        print(f"attention x1 (einsum):    {ms_attn1:8.2f} ms  "
              f"(x{L} = {ms_attn1*L:.1f})", flush=True)
    except Exception as exc:  # noqa: BLE001 — keep profiling other stages
        print(f"attention x1 (einsum):    failed: {exc}", flush=True)

    # Cache scatter write, one layer-equivalent (k payload .at[].set).
    # The donated buffer must be REBOUND each call (k = f(k, ...)) — reusing
    # the stale python ref would hand the jit a deleted buffer.
    kq = jnp.ones((B, 1, nkv, D), jnp.int8)
    lidx = jnp.zeros((B, 1), jnp.int32)
    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]

    def scatter(k, kq, pos):
        return k.at[lidx, bidx, pos].set(kq)

    try:
        f = jax.jit(scatter, donate_argnums=(0,))
        k = cache.k
        for _ in range(n_warm):
            k = f(k, kq, pos)
        sync(k)
        t0 = time.perf_counter()
        for _ in range(n_timed):
            k = f(k, kq, pos)
        sync(k)
        ms_scat1 = (time.perf_counter() - t0) / n_timed * 1e3
        cache = cache._replace(k=k)
        print(f"cache scatter x1 (k):     {ms_scat1:8.2f} ms  "
              f"(x{2*L} = {ms_scat1*2*L:.1f})", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"cache scatter x1 (k):     failed: {exc}", flush=True)

    # Pallas ragged decode kernel at this capacity (if divisible)
    from symmetry_tpu.ops import decode_attention as da
    for bt in (512, 256, 128):
        if T % bt == 0 and bt <= T:
            q3 = jnp.ones((B, nq, D), jnp.bfloat16)
            pal = jax.jit(lambda q3, k, v, ks, vs, kl: da.decode_attention(
                q3, k, v, jnp.int32(0), kl,
                k_scale=ks, v_scale=vs, block_t=bt))
            try:
                ms_pallas1 = timeit(pal, q3, cache.k, cache.v,
                                    cache.k_scale, cache.v_scale, kl)
                print(f"attention x1 (pallas):    {ms_pallas1:8.2f} ms  "
                      f"(x{L} = {ms_pallas1*L:.1f})", flush=True)
            except Exception as exc:  # noqa: BLE001
                print(f"attention x1 (pallas):    failed: {exc}", flush=True)
            break

    # bandwidth sanity: weight bytes + kv bytes
    wb = sum(np.prod(x.shape) * x.dtype.itemsize
             for x in jax.tree.leaves(params))
    kvb = (2 * L * B * T * nkv * D * 1
           + 2 * L * B * nkv * T * 4)
    print(f"weight bytes: {wb/1e9:.2f} GB  kv bytes: {kvb/1e9:.2f} GB")


if __name__ == "__main__":
    main()
