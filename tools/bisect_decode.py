"""Ablate decode-step components to locate the per-slot compute overhead.

profile_decode shows ~7.6 ms of the B=128 step scales with batch but not
with KV or weight traffic (trunk: 16.9 ms at B=1, 24.5 ms at B=128 with a
64-entry cache). This monkeypatches one component at a time out of the
trunk and re-times it; the delta attributes the overhead.

Run: python tools/bisect_decode.py [--slots 128 --max-seq 640]
"""

from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _bench_util import sync  # noqa: E402


def time_trunk(cfg, params, B, T, n=15):
    import time

    from symmetry_tpu.models import llama

    cache = llama.init_cache(cfg, B, T, jnp.bfloat16, quantized=True)
    cache = cache._replace(lengths=jnp.full((B,), T - (n + 4), jnp.int32))
    tok = jnp.ones((B, 1), jnp.int32)
    trunk = jax.jit(lambda p, t, c: llama.forward_hidden(p, cfg, t, c),
                    donate_argnums=(2,))
    for _ in range(3):
        h, cache = trunk(params, tok, cache)
    sync(h)
    t0 = time.perf_counter()
    for _ in range(n):
        h, cache = trunk(params, tok, cache)
    sync(h)
    return (time.perf_counter() - t0) / n * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--slots", type=int, default=128)
    ap.add_argument("--max-seq", type=int, default=640)
    args = ap.parse_args()

    from symmetry_tpu.models import llama
    from symmetry_tpu.ops import quant

    cfg = llama.preset(args.preset)
    B, T = args.slots, args.max_seq
    params = llama.init_params(cfg, jax.random.key(0), jnp.bfloat16,
                               quantize=True)

    if os.environ.get("BISECT_W8A8"):
        ab_w8a8(cfg, params, B, T)
        return

    base = time_trunk(cfg, params, B, T)
    print(f"baseline:        {base:7.2f} ms", flush=True)

    # --- no rope (identity)
    real_rope = llama.apply_rope
    llama.apply_rope = lambda x, positions, theta=0.0: x
    ms = time_trunk(cfg, params, B, T)
    llama.apply_rope = real_rope
    print(f"rope ablated:    {ms:7.2f} ms  (rope cost ~{base - ms:5.2f})",
          flush=True)

    # --- no kv quantize (write zeros: kills abs/round/clip chain)
    real_qkv = quant.quantize_kv

    def fake_qkv(x):
        q = jnp.zeros(x.shape, jnp.int8)
        s = jnp.ones(x.shape[:-1], jnp.float32)
        return q, s

    llama_quant = sys.modules["symmetry_tpu.models.llama"]
    # _layer imports quantize_kv lazily from ops.quant, so patch the module
    quant.quantize_kv = fake_qkv
    ms = time_trunk(cfg, params, B, T)
    quant.quantize_kv = real_qkv
    print(f"kvquant ablated: {ms:7.2f} ms  (quantize_kv ~{base - ms:5.2f})",
          flush=True)

    # --- attention bypassed entirely (q passes through)
    real_attn = llama.gqa_attention

    def fake_attn(q, k, v, positions, kv_length, **kw):
        return q

    llama.gqa_attention = fake_attn
    ms = time_trunk(cfg, params, B, T)
    llama.gqa_attention = real_attn
    print(f"attn ablated:    {ms:7.2f} ms  (attention ~{base - ms:5.2f})",
          flush=True)

    # --- rms_norm ablated
    real_norm = llama.rms_norm
    llama.rms_norm = lambda x, w, eps: x
    ms = time_trunk(cfg, params, B, T)
    llama.rms_norm = real_norm
    print(f"norm ablated:    {ms:7.2f} ms  (rms_norm ~{base - ms:5.2f})",
          flush=True)


def ab_w8a8(cfg, params, B, T):
    """In-trunk A/B of the w8a8 Pallas routing (ops/qmm.py)."""
    from symmetry_tpu.ops import qmm

    ms_on = time_trunk(cfg, params, B, T)
    print(f"w8a8 kernel ON:  {ms_on:7.2f} ms", flush=True)
    real = qmm.supports
    qmm.supports = lambda *a, **k: False
    ms_off = time_trunk(cfg, params, B, T)
    qmm.supports = real
    print(f"w8a8 kernel OFF: {ms_off:7.2f} ms", flush=True)


if __name__ == "__main__":
    main()
