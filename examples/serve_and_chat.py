"""End-to-end demo: server + TPU-native provider + client in one process.

Runs the full three-role network (broker, provider with the in-process JAX
engine, client) over real TCP loopback and streams a chat completion.
Works on CPU (tiny random-weight model) — on a TPU host, point
`model_preset` at llama3-8b and `checkpoint_path` at an HF safetensors dir.

    PYTHONPATH=. python examples/serve_and_chat.py
"""

import asyncio

from symmetry_tpu.client.client import SymmetryClient
from symmetry_tpu.identity import Identity
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.server.broker import SymmetryServer
from symmetry_tpu.transport.tcp import TcpTransport


async def main() -> None:
    transport = TcpTransport()

    server_ident = Identity.generate()
    server = SymmetryServer(server_ident, transport)
    await server.start("127.0.0.1:4848")

    config = ConfigManager(config={
        "name": "demo-provider",
        "public": True,
        "serverKey": server_ident.public_hex,
        "modelName": "tiny:demo",
        "apiProvider": "tpu_native",
        "dataCollectionEnabled": False,
        "tpu": {
            "model_preset": "tiny",        # llama3-8b on a real TPU host
            "dtype": "float32",            # bfloat16 on TPU
            "quantization": "int8",
            "kv_quantization": "int8",
            "max_batch_size": 4,
            "max_seq_len": 256,
            "prefill_buckets": [64, 128],
            "decode_block": 8,
        },
    })
    provider = SymmetryProvider(config, transport=transport,
                                server_address="127.0.0.1:4848")
    await provider.start("127.0.0.1:0")
    await provider.wait_registered()

    client = SymmetryClient(Identity.generate(), transport)
    details = await client.request_provider(
        "127.0.0.1:4848", server_ident.public_key, "tiny:demo")
    print(f"assigned provider {details.peer_key[:12]}… at {details.address}")

    session = await client.connect(details)
    print("assistant> ", end="", flush=True)
    async for delta in session.chat(
            [{"role": "user", "content": "hello from the demo"}],
            max_tokens=32, temperature=0.7):
        print(delta, end="", flush=True)
    print()
    print("provider stats:", provider.stats())

    await session.close()
    await provider.stop(drain_timeout_s=5)
    await server.stop()


if __name__ == "__main__":
    asyncio.run(main())
