"""Two-process demo of the secure transport layer.

Run a listener (responder) and a dialer (initiator) as separate OS processes
talking over a real TCP socket with the authenticated encrypted channel:

    python examples/secure_echo.py listen 127.0.0.1:9410
    python examples/secure_echo.py dial   127.0.0.1:9410 [server_pub_hex]

The dialer sends `inference`-keyed messages; the listener streams back three
`tokenChunk` messages and an `inferenceEnded`, mirroring the shape of the real
provider hot path.
"""

import asyncio
import sys

from symmetry_tpu.identity import Identity
from symmetry_tpu.network.peer import Peer
from symmetry_tpu.protocol.keys import MessageKey
from symmetry_tpu.transport import TcpTransport


async def listen(addr: str) -> None:
    ident = Identity.from_name("echo-server")
    print(f"server identity: {ident.public_hex}", flush=True)
    done = asyncio.Event()

    async def handler(conn):
        peer = await Peer.connect(conn, ident, initiator=False)
        print(f"peer connected: {peer.remote_public_hex[:16]}… from {peer.remote_address}", flush=True)
        async for msg in peer:
            print(f"recv: key={msg.key} data={msg.data}", flush=True)
            if msg.key == MessageKey.INFERENCE:
                for tok in ["Hello", ", ", "world"]:
                    await peer.send(MessageKey.TOKEN_CHUNK, {"token": tok})
                await peer.send(MessageKey.INFERENCE_ENDED, {"tokens": 3})
            elif msg.key == MessageKey.LEAVE:
                done.set()
                return

    t = TcpTransport()
    listener = await t.listen(f"tcp://{addr}", handler)
    print(f"listening on {listener.address}", flush=True)
    await done.wait()
    await listener.close()
    print("server done", flush=True)


async def dial(addr: str, expected_hex: str | None) -> None:
    ident = Identity.from_name("echo-client")
    expected = bytes.fromhex(expected_hex) if expected_hex else None
    t = TcpTransport()
    conn = await t.dial(f"tcp://{addr}")
    peer = await Peer.connect(conn, ident, initiator=True, expected_remote_key=expected)
    print(f"connected; authenticated server = {peer.remote_public_hex}", flush=True)
    await peer.send(MessageKey.INFERENCE, {"messages": [{"role": "user", "content": "hi"}]})
    completion = ""
    while True:
        msg = await peer.recv()
        if msg is None or msg.key == MessageKey.INFERENCE_ENDED:
            print(f"ended: {msg.data if msg else None}", flush=True)
            break
        if msg.key == MessageKey.TOKEN_CHUNK:
            completion += msg.data["token"]
            print(f"chunk: {msg.data['token']!r}", flush=True)
    print(f"completion: {completion!r}", flush=True)
    await peer.send(MessageKey.LEAVE)
    await peer.close()


if __name__ == "__main__":
    mode, addr = sys.argv[1], sys.argv[2]
    if mode == "listen":
        asyncio.run(listen(addr))
    else:
        asyncio.run(dial(addr, sys.argv[3] if len(sys.argv) > 3 else None))
