"""Serverless discovery demo: provider announces on the Kademlia DHT, a
client resolves it by public key and chats — no central server involved.

    PYTHONPATH=. python examples/dht_discovery.py
"""

import asyncio

from symmetry_tpu.client.client import SymmetryClient
from symmetry_tpu.identity import Identity
from symmetry_tpu.network.dht import DHTNode
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.transport.tcp import TcpTransport


async def main() -> None:
    # A bootstrap node — in production any long-lived peer serves this role.
    bootstrap = DHTNode()
    await bootstrap.start("127.0.0.1", 0)
    boot_addr = f"127.0.0.1:{bootstrap.port}"

    ident = Identity.generate()
    config = ConfigManager(config={
        "name": "dht-demo-provider",
        "public": False,                  # no central server at all
        "serverKey": "00" * 32,
        "modelName": "tiny:dht-demo",
        "apiProvider": "echo",
        "dataCollectionEnabled": False,
        "dht": {"host": "127.0.0.1", "bootstrap": [boot_addr]},
    })
    provider = SymmetryProvider(config, transport=TcpTransport(),
                                identity=ident)
    await provider.start("127.0.0.1:0")
    print(f"provider announced; share its public key: {ident.public_hex}")

    client = SymmetryClient(Identity.generate(), TcpTransport())
    details = await client.discover(ident.public_key, [boot_addr])
    print(f"resolved via DHT: model={details.model_name!r} "
          f"address={details.address}")

    session = await client.connect(details)
    text = await session.chat_text(
        [{"role": "user", "content": "discovered you on the DHT"}])
    print(f"assistant> {text}")

    await session.close()
    await provider.stop(drain_timeout_s=3)
    await bootstrap.stop()


if __name__ == "__main__":
    asyncio.run(main())
