#!/usr/bin/env bash
# Installer parity with the reference's install.sh (scaffolds
# ~/.config/symmetry/provider.yaml and installs the CLI; reference
# install.sh:1-62). The TPU build installs from this checkout with pip and
# writes a tpu_native default config instead of an Ollama proxy one.
set -euo pipefail

CONFIG_DIR="${SYMMETRY_CONFIG_DIR:-$HOME/.config/symmetry}"
CONFIG_PATH="$CONFIG_DIR/provider.yaml"
REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"

echo "Installing symmetry-tpu from $REPO_DIR ..."
python3 -m pip install --user "$REPO_DIR"
# Checkout-free alternatives (reference parity: npm global + `pkg` binary):
#   python3 tools/build_dist.py        -> dist/symmetry_tpu-*.whl (pipx/pip
#                                         installable) + dist/symmetry-tpu.pyz
#   python3 symmetry-tpu.pyz provider  -> run any role from the single file

mkdir -p "$CONFIG_DIR"
if [ -f "$CONFIG_PATH" ]; then
    echo "Config already exists at $CONFIG_PATH — leaving it untouched."
else
    DEFAULT_NAME="$(id -un)-tpu"
    NAME="" MODEL="" SERVER_KEY=""
    if [ -t 0 ]; then  # prompt only when stdin is a tty; CI/curl|bash take defaults
        read -r -p "Provider name [$DEFAULT_NAME]: " NAME || true
        read -r -p "Model preset [llama3-8b]: " MODEL || true
        read -r -p "Server key (hex, empty for private provider): " SERVER_KEY || true
    fi
    NAME="${NAME:-$DEFAULT_NAME}"
    MODEL="${MODEL:-llama3-8b}"

    PUBLIC=true
    if [ -z "$SERVER_KEY" ]; then
        PUBLIC=false
        SERVER_KEY="0000000000000000000000000000000000000000000000000000000000000000"
    fi

    cat > "$CONFIG_PATH" <<EOF
# symmetry-tpu provider config (see README.md; field parity with the
# reference provider.yaml plus the tpu: engine section)
name: $NAME
public: $PUBLIC
serverKey: "$SERVER_KEY"
modelName: "$MODEL"
apiProvider: tpu_native
dataCollectionEnabled: false
maxConnections: 16
path: $CONFIG_DIR
tpu:
  model_preset: $MODEL
  dtype: bfloat16
  quantization: int8
  kv_quantization: int8
  max_batch_size: 16
  max_seq_len: 2048
  prefill_buckets: [128, 512, 2048]
  decode_block: 16
  # checkpoint_path: /path/to/hf/safetensors/dir
  # tokenizer_path: /path/to/tokenizer.json
EOF
    echo "Wrote default config to $CONFIG_PATH"
fi

echo
echo "Run the provider with:  symmetry-tpu-provider -c $CONFIG_PATH"
echo "Run a server with:      symmetry-tpu-server"
