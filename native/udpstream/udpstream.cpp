// udpstream: reliable, ordered, frame-preserving streams over UDP.
//
// The TPU-native counterpart of the reference's udx-native dependency (C
// addon under hyperswarm; SURVEY §2.2): multiplexed logical connections on
// one UDP socket, segment sequencing with cumulative ACKs, fixed-RTO
// retransmission, a bounded in-flight window for flow control, and frame
// boundaries preserved via an end-of-frame bit — the contract the Python
// Transport seam expects (symmetry_tpu/transport/base.py). Encryption is
// deliberately NOT here: the Noise layer above the transport owns it
// (symmetry_tpu/network/peer.py), mirroring udx-under-secret-stream.
//
// Single background thread per socket context: socket recv with a short
// timeout doubles as the retransmit/keepalive tick. The C API is blocking
// (condition variables); the Python asyncio adapter runs it in worker
// threads (symmetry_tpu/transport/udp.py).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t MAGIC = 0xD5;
constexpr uint8_t F_SYN = 1;
constexpr uint8_t F_ACK = 2;
constexpr uint8_t F_FIN = 4;
constexpr uint8_t F_DATA = 8;
constexpr uint8_t F_EOFR = 16;  // last segment of a frame
constexpr uint8_t F_RAW = 32;   // connectionless datagram (NAT punch /
                                // rendezvous side-channel, us_send_raw)

constexpr size_t HDR = 16;
constexpr size_t MTU_PAYLOAD = 1200;
constexpr int WINDOW = 128;          // max unacked segments in flight
constexpr int64_t RTO_MS = 200;
constexpr int MAX_RETRIES = 50;      // ~10 s before declaring a peer dead
constexpr int64_t TICK_MS = 20;

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

struct Addr {
  sockaddr_in sa{};
  bool operator<(const Addr& o) const {
    if (sa.sin_addr.s_addr != o.sa.sin_addr.s_addr)
      return sa.sin_addr.s_addr < o.sa.sin_addr.s_addr;
    return sa.sin_port < o.sa.sin_port;
  }
};

struct Segment {
  uint32_t seq;
  uint8_t flags;
  std::vector<uint8_t> payload;
  int64_t sent_at = 0;
  int retries = 0;
};

struct Conn {
  uint32_t id;
  Addr peer;
  bool established = false;
  bool closed = false;       // FIN seen or sent
  bool dead = false;         // retransmit give-up
  // sender
  uint32_t next_seq = 0;
  std::deque<Segment> unacked;
  // receiver
  uint32_t recv_next = 0;                       // next in-order seq expected
  std::map<uint32_t, Segment> ooo;              // out-of-order stash
  std::vector<uint8_t> frame_accum;             // partial frame bytes
  std::deque<std::vector<uint8_t>> frames;      // complete frames ready
};

struct Ctx {
  int fd = -1;
  uint16_t port = 0;
  std::thread loop;
  std::atomic<bool> stop{false};

  std::mutex mu;
  std::condition_variable cv;
  std::map<uint64_t, Conn> conns;               // key: addr-hash<<32 | id
  std::deque<uint64_t> accept_q;
  // connectionless F_RAW datagrams (payload, source) for us_recv_raw
  std::deque<std::pair<Addr, std::vector<uint8_t>>> raw_q;
  std::mt19937 rng{std::random_device{}()};

  uint64_t key_for(const Addr& a, uint32_t id) {
    uint64_t h = (uint64_t(a.sa.sin_addr.s_addr) << 16) ^ a.sa.sin_port;
    return (h << 24) ^ id;  // cheap mix; collisions only break the colliders
  }
};

void pack_hdr(uint8_t* b, uint8_t flags, uint32_t conn, uint32_t seq,
              uint32_t ack, uint16_t len) {
  b[0] = MAGIC;
  b[1] = flags;
  memcpy(b + 2, &conn, 4);
  memcpy(b + 6, &seq, 4);
  memcpy(b + 10, &ack, 4);
  memcpy(b + 14, &len, 2);
}

void send_pkt(Ctx* c, const Addr& to, uint8_t flags, uint32_t conn,
              uint32_t seq, uint32_t ack, const uint8_t* data, uint16_t len) {
  uint8_t buf[HDR + MTU_PAYLOAD];
  pack_hdr(buf, flags, conn, seq, ack, len);
  if (len) memcpy(buf + HDR, data, len);
  sendto(c->fd, buf, HDR + len, 0,
         reinterpret_cast<const sockaddr*>(&to.sa), sizeof(to.sa));
}

void deliver_in_order(Conn& cn) {
  // Pull contiguous segments out of the stash into frames.
  for (;;) {
    auto it = cn.ooo.find(cn.recv_next);
    if (it == cn.ooo.end()) break;
    Segment& s = it->second;
    cn.frame_accum.insert(cn.frame_accum.end(), s.payload.begin(),
                          s.payload.end());
    if (s.flags & F_EOFR) {
      cn.frames.push_back(std::move(cn.frame_accum));
      cn.frame_accum.clear();
    }
    cn.ooo.erase(it);
    cn.recv_next++;
  }
}

void handle_packet(Ctx* c, const Addr& from, const uint8_t* b, ssize_t n) {
  if (n < ssize_t(HDR) || b[0] != MAGIC) return;
  uint8_t flags = b[1];
  uint32_t conn_id, seq, ack;
  uint16_t len;
  memcpy(&conn_id, b + 2, 4);
  memcpy(&seq, b + 6, 4);
  memcpy(&ack, b + 10, 4);
  memcpy(&len, b + 14, 2);
  if (ssize_t(HDR) + len > n) return;

  std::lock_guard<std::mutex> lk(c->mu);

  if (flags & F_RAW) {
    // Side-channel datagram: same socket (the NAT mapping under the
    // streams), no connection state. Bounded queue: punch bursts are
    // small and stale entries are worthless.
    if (c->raw_q.size() < 256) {
      c->raw_q.emplace_back(from, std::vector<uint8_t>(b + HDR, b + HDR + len));
      c->cv.notify_all();
    }
    return;
  }

  uint64_t key = c->key_for(from, conn_id);
  auto it = c->conns.find(key);

  if (flags & F_SYN) {
    if (flags & F_ACK) {              // dialer side: SYN-ACK completes
      if (it != c->conns.end()) {
        it->second.established = true;
        c->cv.notify_all();
      }
    } else {                          // listener side: new connection
      if (it == c->conns.end()) {
        Conn cn;
        cn.id = conn_id;
        cn.peer = from;
        cn.established = true;
        c->conns.emplace(key, std::move(cn));
        c->accept_q.push_back(key);
      }
      send_pkt(c, from, F_SYN | F_ACK, conn_id, 0, 0, nullptr, 0);
      c->cv.notify_all();
    }
    return;
  }
  if (it == c->conns.end()) return;
  Conn& cn = it->second;

  if (flags & F_ACK) {                // cumulative: drop acked segments
    while (!cn.unacked.empty() && cn.unacked.front().seq < ack)
      cn.unacked.pop_front();
    c->cv.notify_all();
  }
  if (flags & F_DATA) {
    if (seq >= cn.recv_next && cn.ooo.size() < 4 * WINDOW) {
      Segment s;
      s.seq = seq;
      s.flags = flags;
      s.payload.assign(b + HDR, b + HDR + len);
      cn.ooo.emplace(seq, std::move(s));
      deliver_in_order(cn);
    }
    // Always (re-)ack what we have; lost ACKs are recovered here.
    send_pkt(c, cn.peer, F_ACK, cn.id, 0, cn.recv_next, nullptr, 0);
    if (!cn.frames.empty()) c->cv.notify_all();
  }
  if (flags & F_FIN) {
    cn.closed = true;
    send_pkt(c, cn.peer, F_ACK, cn.id, 0, cn.recv_next, nullptr, 0);
    c->cv.notify_all();
  }
}

void tick_retransmits(Ctx* c) {
  int64_t now = now_ms();
  std::lock_guard<std::mutex> lk(c->mu);
  for (auto& [key, cn] : c->conns) {
    if (cn.dead) continue;
    for (auto& s : cn.unacked) {
      if (now - s.sent_at < RTO_MS) continue;
      if (++s.retries > MAX_RETRIES) {
        cn.dead = true;
        c->cv.notify_all();
        break;
      }
      s.sent_at = now;
      send_pkt(c, cn.peer, s.flags, cn.id, s.seq, 0, s.payload.data(),
               uint16_t(s.payload.size()));
    }
  }
}

void loop_fn(Ctx* c) {
  uint8_t buf[HDR + MTU_PAYLOAD + 64];
  int64_t last_tick = 0;
  while (!c->stop.load()) {
    Addr from;
    socklen_t sl = sizeof(from.sa);
    ssize_t n = recvfrom(c->fd, buf, sizeof(buf), 0,
                         reinterpret_cast<sockaddr*>(&from.sa), &sl);
    if (n > 0) handle_packet(c, from, buf, n);
    int64_t now = now_ms();
    if (now - last_tick >= TICK_MS) {
      last_tick = now;
      tick_retransmits(c);
    }
  }
}

}  // namespace

extern "C" {

void* us_create(const char* bind_ip, int port) {
  auto* c = new Ctx();
  c->fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (c->fd < 0) { delete c; return nullptr; }
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, bind_ip, &sa.sin_addr) != 1) {
    close(c->fd);
    delete c;
    return nullptr;
  }
  if (bind(c->fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0) {
    close(c->fd);
    delete c;
    return nullptr;
  }
  socklen_t sl = sizeof(sa);
  getsockname(c->fd, reinterpret_cast<sockaddr*>(&sa), &sl);
  c->port = ntohs(sa.sin_port);
  timeval tv{0, int(TICK_MS) * 1000};
  setsockopt(c->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  c->loop = std::thread(loop_fn, c);
  return c;
}

int us_port(void* h) { return static_cast<Ctx*>(h)->port; }

// Returns a connection key (>0), or 0 on timeout/failure.
uint64_t us_dial(void* h, const char* ip, int port, int timeout_ms) {
  auto* c = static_cast<Ctx*>(h);
  Addr peer;
  peer.sa.sin_family = AF_INET;
  peer.sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, ip, &peer.sa.sin_addr) != 1) return 0;

  uint64_t key;
  uint32_t id;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    id = c->rng();
    key = c->key_for(peer, id);
    Conn cn;
    cn.id = id;
    cn.peer = peer;
    c->conns.emplace(key, std::move(cn));
  }
  int64_t deadline = now_ms() + timeout_ms;
  while (now_ms() < deadline) {
    send_pkt(c, peer, F_SYN, id, 0, 0, nullptr, 0);
    std::unique_lock<std::mutex> lk(c->mu);
    c->cv.wait_for(lk, std::chrono::milliseconds(RTO_MS), [&] {
      auto it = c->conns.find(key);
      return it != c->conns.end() && it->second.established;
    });
    auto it = c->conns.find(key);
    if (it != c->conns.end() && it->second.established) return key;
  }
  std::lock_guard<std::mutex> lk(c->mu);
  c->conns.erase(key);
  return 0;
}

uint64_t us_accept(void* h, int timeout_ms) {
  auto* c = static_cast<Ctx*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  if (!c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                      [&] { return !c->accept_q.empty() || c->stop.load(); }))
    return 0;
  if (c->accept_q.empty()) return 0;
  uint64_t key = c->accept_q.front();
  c->accept_q.pop_front();
  return key;
}

// Send one frame (fragmented into MTU segments). Blocks while the window is
// full. Returns 0 on success, -1 if the connection is closed/dead.
int us_send(void* h, uint64_t key, const uint8_t* data, int len) {
  auto* c = static_cast<Ctx*>(h);
  int off = 0;
  do {
    int chunk = len - off > int(MTU_PAYLOAD) ? int(MTU_PAYLOAD) : len - off;
    bool last = off + chunk >= len;
    std::unique_lock<std::mutex> lk(c->mu);
    auto it = c->conns.find(key);
    if (it == c->conns.end()) return -1;
    c->cv.wait(lk, [&] {
      auto i2 = c->conns.find(key);
      return i2 == c->conns.end() || i2->second.dead || i2->second.closed ||
             int(i2->second.unacked.size()) < WINDOW;
    });
    it = c->conns.find(key);
    if (it == c->conns.end() || it->second.dead || it->second.closed)
      return -1;
    Conn& cn = it->second;
    Segment s;
    s.seq = cn.next_seq++;
    s.flags = uint8_t(F_DATA | (last ? F_EOFR : 0));
    s.payload.assign(data + off, data + off + chunk);
    s.sent_at = now_ms();
    send_pkt(c, cn.peer, s.flags, cn.id, s.seq, 0, s.payload.data(),
             uint16_t(chunk));
    cn.unacked.push_back(std::move(s));
    off += chunk;
  } while (off < len);
  return 0;
}

// Receive one complete frame into buf. Returns its length, 0 on timeout,
// -1 on clean close, -2 if buf is too small (frame stays queued), -3 dead.
int us_recv(void* h, uint64_t key, uint8_t* buf, int cap, int timeout_ms) {
  auto* c = static_cast<Ctx*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  auto ready = [&] {
    auto it = c->conns.find(key);
    return it == c->conns.end() || !it->second.frames.empty() ||
           it->second.closed || it->second.dead || c->stop.load();
  };
  if (!c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready))
    return 0;
  auto it = c->conns.find(key);
  if (it == c->conns.end()) return -1;
  Conn& cn = it->second;
  if (!cn.frames.empty()) {
    auto& f = cn.frames.front();
    if (int(f.size()) > cap) return -2;
    int n = int(f.size());
    memcpy(buf, f.data(), f.size());
    cn.frames.pop_front();
    return n;
  }
  if (cn.dead) return -3;
  if (cn.closed) return -1;
  return 0;
}

void us_close(void* h, uint64_t key) {
  auto* c = static_cast<Ctx*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  auto it = c->conns.find(key);
  if (it == c->conns.end()) return;
  send_pkt(c, it->second.peer, F_FIN, it->second.id, 0, 0, nullptr, 0);
  it->second.closed = true;
  c->cv.notify_all();
}

int us_send_raw(void* h, const char* ip, int port, const uint8_t* data,
                int len) {
  // Connectionless datagram from THIS ctx's socket — the packet's source
  // is the same (addr, port) the stream protocol uses, which is what
  // makes it useful: it opens/keeps-open the NAT mapping that a
  // subsequent us_dial (or an inbound SYN) will traverse.
  Ctx* c = static_cast<Ctx*>(h);
  if (len < 0 || size_t(len) > MTU_PAYLOAD) return 0;
  Addr to;
  to.sa.sin_family = AF_INET;
  to.sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, ip, &to.sa.sin_addr) != 1) return 0;
  send_pkt(c, to, F_RAW, 0, 0, 0, data, uint16_t(len));
  return 1;
}

int us_recv_raw(void* h, uint8_t* buf, int cap, char* ip_out, int* port_out,
                int timeout_ms) {
  // Pop one raw datagram; returns its length, or -1 on timeout. ip_out
  // must hold >= 16 bytes (INET_ADDRSTRLEN).
  Ctx* c = static_cast<Ctx*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  if (!c->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                      [&] { return !c->raw_q.empty() || c->stop.load(); }))
    return -1;
  if (c->raw_q.empty()) return -1;
  auto [from, payload] = std::move(c->raw_q.front());
  c->raw_q.pop_front();
  int n = int(payload.size());
  if (n > cap) n = cap;
  memcpy(buf, payload.data(), n);
  inet_ntop(AF_INET, &from.sa.sin_addr, ip_out, 16);
  *port_out = ntohs(from.sa.sin_port);
  return n;
}

void us_destroy(void* h) {
  auto* c = static_cast<Ctx*>(h);
  c->stop.store(true);
  c->cv.notify_all();
  if (c->loop.joinable()) c->loop.join();
  close(c->fd);
  delete c;
}

}  // extern "C"
