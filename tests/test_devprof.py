"""symprof (utils/devprof.py) + benchdiff (tools/benchdiff.py) tests.

Three layers, matching the PR's contract:

  - DeviceProfiler unit behavior: the 1-in-N cadence, the
    probed-completion → next-begin gap pairing, stats/gap-share shapes,
    the Perfetto device component, and the DISABLED-mode overhead guard
    (one branch per dispatch, same discipline as the metrics registry
    and the fault injector).
  - Engine integration: a tiny engine with profile_sample on books
    per-kind device durations through real dispatches, and the
    scheduler's stats() carries the devprof block; profile_sample=0
    books nothing and compiles no extra anything.
  - benchdiff verdict logic: direction/min-effect policies, IQR noise
    bands over a baseline series, the config-fingerprint refusal, exit
    codes, and the markdown table — plus bench.stamp_result fingerprint
    stability (same config → same stamp; any knob change → different).
"""

import json
import os
import sys
import time

import pytest

from symmetry_tpu.utils.devprof import DISPATCH_KINDS, DeviceProfiler
from symmetry_tpu.utils.metrics import METRICS

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.benchdiff import compare, flatten, policy_for  # noqa: E402
from tools.benchdiff import main as benchdiff_main  # noqa: E402


class TestDeviceProfiler:
    def test_disabled_is_inert(self):
        dp = DeviceProfiler(0)
        assert not dp.enabled
        # The engine never calls begin/probe with the knob off (the
        # `if dp.enabled` guard is the contract), but even direct calls
        # must not blow up or book anything real.
        dp.probe("decode_block", None, 0.0)
        assert dp.stats()["probes"] == {}
        assert dp.gap_share() is None

    def test_disabled_mode_overhead_guard(self):
        """The off-mode cost the engine pays per dispatch is ONE
        attribute load + branch (`if dp.enabled:`). Same bound
        discipline as the metrics registry's disabled mode: 200k
        guarded dispatch sites must stay far under the time one real
        dispatch costs."""
        dp = DeviceProfiler(0)
        t0 = time.perf_counter()
        acc = 0.0
        for _ in range(200_000):
            if dp.enabled:  # the exact engine-side guard shape
                acc += dp.begin()
        dt = time.perf_counter() - t0
        assert acc == 0.0
        assert dt < 0.5, f"disabled-mode: {dt:.3f}s for 200k guards"
        # ~an engine dispatch is >= 100 us even on CPU; the guard must
        # be noise beside it (one guard < 0.1% of 100 us).
        assert (dt / 200_000) < 1e-7 * 100

    def test_cadence_probes_one_in_n_per_kind(self):
        """The cadence is per KIND: a rare kind interleaved with a
        frequent one must still get its 1-in-N probes instead of the
        frequent kind absorbing every slot of a shared counter."""
        dp = DeviceProfiler(4)
        for _ in range(12):
            t0 = dp.begin()
            dp.probe("decode_block", 1.23, t0)  # plain float: pytree leaf
        for _ in range(4):
            t0 = dp.begin()
            dp.probe("prefill", 1.23, t0)
        stats = dp.stats()
        assert stats["dispatches"] == {"decode_block": 12, "prefill": 4}
        assert stats["probes"] == {"decode_block": 3, "prefill": 1}
        assert stats["device_s"]["decode_block"]["count"] == 3
        assert stats["device_s"]["prefill"]["count"] == 1

    def test_gap_pairs_probe_with_next_begin(self):
        dp = DeviceProfiler(1)
        t0 = dp.begin()
        dp.probe("prefill", 0.0, t0)
        assert dp.stats()["dispatch_gap_s"]["count"] == 0  # not yet
        time.sleep(0.01)
        dp.begin()  # closes the pending gap
        stats = dp.stats()
        assert stats["dispatch_gap_s"]["count"] == 1
        assert stats["dispatch_gap_s"]["p50"] >= 0.008
        share = dp.gap_share()
        assert share is not None and 0.0 < share <= 1.0
        # begin() without a pending probe adds NO gap (an unprobed
        # dispatch's completion time is unknown — no fabricated idle).
        dp.begin()
        dp.begin()
        assert dp.stats()["dispatch_gap_s"]["count"] == 1

    def test_probe_failure_never_raises(self):
        class Boom:
            def __jax_array__(self):  # pragma: no cover — never reached
                raise RuntimeError("nope")

        dp = DeviceProfiler(1)
        t0 = dp.begin()
        # block_until_ready on a non-pytree-of-arrays may raise inside
        # jax; the probe must swallow it — diagnostics never fail work.
        dp.probe("verify", object(), t0)
        assert True  # reaching here IS the assertion

    def test_component_is_perfetto_ready(self):
        from symmetry_tpu.utils.trace import export_perfetto

        dp = DeviceProfiler(1)
        for kind in ("prefill", "decode_block"):
            t0 = dp.begin()
            dp.probe(kind, 7.0, t0)
        dp.begin()
        comp = dp.component("device")
        assert comp["name"] == "device"
        perfetto = export_perfetto([comp])
        names = {e["name"] for e in perfetto["traceEvents"]
                 if e.get("ph") == "X"}
        assert {"prefill", "decode_block", "dispatch_gap"} <= names
        assert all(e["ts"] >= 0 for e in perfetto["traceEvents"]
                   if e.get("ph") == "X")

    def test_metrics_families_emitted(self):
        from symmetry_tpu.utils.metrics import MetricName

        dp = DeviceProfiler(1)
        t0 = dp.begin()
        dp.probe("decode_block", 0.5, t0)
        dp.begin()
        snap = METRICS.snapshot(compact=True)["families"]
        assert MetricName.DEVICE_DISPATCH in snap
        assert MetricName.DEVICE_PROBES in snap
        assert MetricName.DISPATCH_GAP in snap
        assert MetricName.DISPATCH_GAP_SHARE in snap
        probes = snap[MetricName.DEVICE_PROBES]["series"]
        assert any(s["labels"].get("kind") == "decode_block"
                   for s in probes)

    def test_kind_vocabulary_documented(self):
        # The engine's hook kinds and the documented set must agree —
        # the smoke asserts per-kind slices by these names.
        assert set(DISPATCH_KINDS) == {
            "prefill", "chunk", "decode_block", "verify", "adopt",
            "seed_gather", "scatter"}


class TestEngineIntegration:
    @pytest.fixture(scope="class")
    def engine_mod(self):
        import jax
        import jax.numpy as jnp

        from symmetry_tpu.engine.engine import InferenceEngine
        from symmetry_tpu.engine.tokenizer import ByteTokenizer
        from symmetry_tpu.models import init_params, preset

        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        return cfg, params, InferenceEngine, ByteTokenizer, jnp

    def test_probed_engine_books_kinds_and_gaps(self, engine_mod):
        from symmetry_tpu.engine.engine import SamplingParams

        cfg, params, InferenceEngine, ByteTokenizer, jnp = engine_mod
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32,
            decode_block=2, profile_sample=1)
        engine.warmup()
        engine.prefill_and_insert(0, list(b"hello"), SamplingParams())
        for _ in range(3):
            engine.decode_steps()
        stats = engine.devprof.stats()
        assert stats["probes"].get("prefill", 0) >= 1
        assert stats["probes"].get("decode_block", 0) >= 3
        assert stats["device_s"]["decode_block"]["p50"] is not None
        assert stats["dispatch_gap_s"]["count"] >= 1
        assert stats["gap_share"] is not None

    def test_scheduler_stats_carry_devprof_block(self, engine_mod):
        from symmetry_tpu.engine.scheduler import Scheduler

        cfg, params, InferenceEngine, ByteTokenizer, jnp = engine_mod
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32,
            decode_block=2, profile_sample=1)
        sched = Scheduler(engine)
        assert "devprof" in sched.stats()
        off = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32,
            decode_block=2)
        assert "devprof" not in Scheduler(off).stats()


class TestBenchStamp:
    def _mk(self, **over):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench

        result = {"value": 100.0, "unit": "tok/s"}
        cfg = {"slots": 2, "clients": 8, "quant": "int8", **over}
        return bench.stamp_result(dict(result), cfg, "smoke")

    def test_stamp_is_stable_and_config_sensitive(self):
        a, b = self._mk(), self._mk()
        assert a["schema"] == 1
        assert a["config_fingerprint"] == b["config_fingerprint"]
        assert a["config"]["mode"] == "smoke"
        c = self._mk(slots=4)
        assert c["config_fingerprint"] != a["config_fingerprint"]


class TestBenchdiff:
    def _capture(self, value=100.0, ttft=1.0, fp="aaaa", **extra):
        return {"schema": 1, "git_sha": "deadbeef", "written_at": 0,
                "config": {"mode": "smoke", "slots": 2},
                "config_fingerprint": fp,
                "metric": "x", "unit": "tok/s",
                "value": value, "ttft_p50_s": ttft,
                "tokens_streamed": 4096, **extra}

    def test_flatten_skips_meta_and_nests(self):
        flat = flatten(self._capture(engine={"decode_step_ms": 2.0}))
        assert flat["value"] == 100.0
        assert flat["engine.decode_step_ms"] == 2.0
        assert "config.slots" not in flat
        assert "schema" not in flat

    def test_policies_match_expected_directions(self):
        assert policy_for("value") == ("higher", 0.03)
        assert policy_for("ttft_p50_s")[0] == "lower"
        assert policy_for("engine.decode_step_ms")[0] == "lower"
        assert policy_for("devprof.gap_share")[0] == "lower"
        assert policy_for("shared_prefix.ttft_p50_cached_s")[0] == "lower"
        assert policy_for("tokens_streamed") is None  # workload-sized

    def test_pairwise_verdicts(self):
        base = self._capture()
        rows = compare([base], self._capture(value=80.0, ttft=1.5))
        by = {r["metric"]: r for r in rows}
        assert by["value"]["verdict"] == "REGRESSED"       # -20% tok/s
        assert by["ttft_p50_s"]["verdict"] == "REGRESSED"  # +50% latency
        assert by["tokens_streamed"]["verdict"] == "info"
        rows = compare([base], self._capture(value=110.0, ttft=0.5))
        by = {r["metric"]: r for r in rows}
        assert by["value"]["verdict"] == "improved"
        assert by["ttft_p50_s"]["verdict"] == "improved"
        # Inside the min-effect band: ok, regardless of sign.
        rows = compare([base], self._capture(value=99.0, ttft=1.02))
        by = {r["metric"]: r for r in rows}
        assert by["value"]["verdict"] == "ok"
        assert by["ttft_p50_s"]["verdict"] == "ok"

    def test_series_iqr_widens_the_band(self):
        # A noisy metric: baseline runs spread 80..120, so a candidate
        # at 85 is within the measured noise even though it is >3%
        # below the last baseline — the IQR band must absorb it.
        series = [self._capture(value=v)
                  for v in (80.0, 100.0, 120.0, 95.0, 105.0)]
        rows = compare(series, self._capture(value=85.0))
        by = {r["metric"]: r for r in rows}
        assert by["value"]["verdict"] == "ok"
        # A genuinely-off candidate still regresses through the band.
        rows = compare(series, self._capture(value=40.0))
        by = {r["metric"]: r for r in rows}
        assert by["value"]["verdict"] == "REGRESSED"

    def _write(self, tmp_path, name, obj):
        p = tmp_path / name
        p.write_text(json.dumps(obj))
        return str(p)

    def test_cli_exit_codes_and_markdown(self, tmp_path, capsys):
        base = self._write(tmp_path, "base.json", self._capture())
        same = self._write(tmp_path, "same.json", self._capture())
        worse = self._write(tmp_path, "worse.json",
                            self._capture(value=50.0))
        out_md = tmp_path / "delta.md"
        assert benchdiff_main([base, same, "--out", str(out_md)]) == 0
        text = capsys.readouterr().out
        assert "| metric |" in text and "REGRESSED" not in text
        assert out_md.read_text().startswith("# benchdiff")
        assert benchdiff_main([base, worse]) == 1
        assert "REGRESSED" in capsys.readouterr().out

    def test_cli_refuses_fingerprint_mismatch(self, tmp_path, capsys):
        base = self._write(tmp_path, "base.json", self._capture())
        other = self._write(
            tmp_path, "other.json",
            self._capture(fp="bbbb") | {"config": {"mode": "smoke",
                                                   "slots": 99}})
        assert benchdiff_main([base, other]) == 2
        err = capsys.readouterr().err
        assert "REFUSING" in err and "slots" in err
        # --force compares anyway and names the differing knobs.
        rc = benchdiff_main([base, other, "--force"])
        assert rc in (0, 1)
        assert "forced" in capsys.readouterr().err

    def test_cli_refuses_unstamped_without_force(self, tmp_path, capsys):
        cap = self._capture()
        legacy = {k: v for k, v in cap.items()
                  if k not in ("schema", "config", "config_fingerprint")}
        base = self._write(tmp_path, "legacy.json", legacy)
        cand = self._write(tmp_path, "cand.json", self._capture())
        assert benchdiff_main([base, cand]) == 2
        assert "unstamped" in capsys.readouterr().err
        assert benchdiff_main([base, cand, "--force"]) in (0, 1)

    def test_cli_json_mode(self, tmp_path, capsys):
        base = self._write(tmp_path, "base.json", self._capture())
        worse = self._write(tmp_path, "worse.json",
                            self._capture(value=50.0))
        assert benchdiff_main([base, worse, "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["regressed"] is True
        assert any(r["verdict"] == "REGRESSED" for r in payload["rows"])


class TestCaptureDeviceProfile:
    def test_capture_writes_artifacts_and_single_flights(self, tmp_path):
        import threading

        from symmetry_tpu.utils.devprof import capture_device_profile

        path = capture_device_profile(str(tmp_path), duration_s=0.05)
        assert os.path.isdir(path)
        # Concurrent capture refused while one holds the window.
        hold = threading.Thread(target=capture_device_profile,
                                args=(str(tmp_path),),
                                kwargs={"duration_s": 0.5})
        hold.start()
        time.sleep(0.15)
        with pytest.raises(RuntimeError, match="already running"):
            capture_device_profile(str(tmp_path), duration_s=0.05)
        hold.join()
