"""Protocol layer: framing, envelopes, keys."""

import pytest

from symmetry_tpu.protocol import (
    FrameReader,
    MAX_FRAME_SIZE,
    MessageKey,
    create_message,
    encode_frame,
    parse_message,
)
from symmetry_tpu.protocol.framing import FrameError
from symmetry_tpu.protocol.keys import SERVER_MESSAGE_KEYS, normalize_key


def test_frame_roundtrip():
    reader = FrameReader()
    payloads = [b"a", b"", b"x" * 100_000, bytes(range(256))]
    stream = b"".join(encode_frame(p) for p in payloads)
    out = []
    # Feed in adversarially small chunks to prove incremental parsing.
    for i in range(0, len(stream), 7):
        out.extend(reader.feed(stream[i : i + 7]))
    assert out == payloads


def test_frame_boundary_preserved_across_coalesced_writes():
    # The failure mode the reference has (unframed JSON, one write != one read):
    # two messages coalesced into one chunk must still parse as two frames.
    reader = FrameReader()
    chunk = encode_frame(b'{"key":"ping"}') + encode_frame(b'{"key":"pong"}')
    assert list(reader.feed(chunk)) == [b'{"key":"ping"}', b'{"key":"pong"}']


def test_oversized_frame_rejected():
    reader = FrameReader()
    import struct

    with pytest.raises(FrameError):
        list(reader.feed(struct.pack(">I", MAX_FRAME_SIZE + 1)))


def test_message_roundtrip():
    raw = create_message(MessageKey.INFERENCE, {"messages": [{"role": "user", "content": "hi"}]})
    msg = parse_message(raw)
    assert msg is not None
    assert msg.key == MessageKey.INFERENCE
    assert msg.data["messages"][0]["content"] == "hi"


def test_message_without_data():
    msg = parse_message(create_message(MessageKey.PING))
    assert msg is not None and msg.key == MessageKey.PING and msg.data is None


def test_malformed_messages_return_none():
    assert parse_message(b"not json") is None
    assert parse_message(b"[1,2,3]") is None
    assert parse_message(b'{"nokey":1}') is None
    assert parse_message(b'{"key":42}') is None
    assert parse_message(None) is None


def test_reference_vocabulary_present():
    # The de-facto protocol spec from reference src/constants.ts:3-20.
    for key in [
        "challenge", "heartbeat", "inference", "inferenceEnded", "join", "joinAck",
        "leave", "newConversation", "ping", "pong", "providerDetails",
        "reportCompletion", "requestProvider", "sessionValid", "verifySession",
    ]:
        assert key in SERVER_MESSAGE_KEYS


def test_reference_misspelling_normalized():
    # Reference spells it `conectionSize` (src/constants.ts:5); we accept it.
    assert normalize_key("conectionSize") == MessageKey.CONNECTION_SIZE
    msg = parse_message(create_message("conectionSize", 3))
    assert msg.key == MessageKey.CONNECTION_SIZE
