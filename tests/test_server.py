"""Server: registry semantics, session tokens, load balancing."""


from symmetry_tpu.identity import Identity
from symmetry_tpu.server import tokens
from symmetry_tpu.server.registry import Registry


def _add(reg, key, model="m1", maxc=10, conns=0):
    reg.upsert_provider(
        peer_key=key, discovery_key="d" + key, model_name=model,
        max_connections=maxc,
    )
    if conns:
        reg.set_connections(key, conns)


def test_upsert_and_select_least_loaded():
    reg = Registry()
    _add(reg, "p1", conns=5)
    _add(reg, "p2", conns=1)
    _add(reg, "p3", conns=9)
    pick = reg.select_provider("m1")
    assert pick.peer_key == "p2"  # least-loaded wins (readme.md "The Tower…")


def test_select_respects_model_and_capacity():
    reg = Registry()
    _add(reg, "p1", model="llama3:8b", maxc=2, conns=2)   # full
    _add(reg, "p2", model="mistral-7b")
    assert reg.select_provider("llama3:8b") is None        # at capacity
    assert reg.select_provider("mistral-7b").peer_key == "p2"
    assert reg.select_provider("nonexistent") is None


def test_offline_excluded_and_restart_resets():
    reg = Registry()
    _add(reg, "p1")
    reg.set_offline("p1")
    assert reg.select_provider("m1") is None
    # Rejoin brings it back.
    _add(reg, "p1")
    assert reg.select_provider("m1").peer_key == "p1"


def test_load_normalized_by_capacity():
    reg = Registry()
    _add(reg, "big", maxc=100, conns=10)    # 10% loaded
    _add(reg, "small", maxc=2, conns=1)     # 50% loaded
    assert reg.select_provider("m1").peer_key == "big"


def test_steering_prefers_smaller_reported_backlog():
    """A provider reporting engine backlog (queued) must stop receiving
    assignments while a less-backlogged one exists — the router-side half
    of overload shedding."""
    reg = Registry()
    _add(reg, "busy", conns=1)
    _add(reg, "idle", conns=3)   # more connections, but no backlog
    reg.set_metrics("busy", {"queued": 64, "shed": 12})
    reg.set_metrics("idle", {"queued": 0})
    assert reg.select_provider("m1").peer_key == "idle"
    # Backlog drains → connection-load order applies again.
    reg.set_metrics("busy", {"queued": 0})
    assert reg.select_provider("m1").peer_key == "busy"
    # A malformed report must not poison steering.
    reg.set_metrics("busy", {"queued": "garbage"})
    assert reg.select_provider("m1").peer_key == "busy"


def test_sessions_and_completions():
    reg = Registry()
    _add(reg, "p1")
    reg.create_session(session_id="s1", peer_key="p1", client_key="c1",
                       model_name="m1", ttl_s=60)
    assert reg.session_valid("s1")
    assert not reg.session_valid("nope")
    reg.create_session(session_id="s2", peer_key="p1", client_key="c1",
                       model_name="m1", ttl_s=-1)  # already expired
    assert not reg.session_valid("s2")
    reg.report_completion(peer_key="p1", session_id="s1", tokens=42)


def test_stale_provider_detection():
    reg = Registry()
    _add(reg, "p1")
    assert reg.stale_providers(older_than_s=60) == []
    assert reg.stale_providers(older_than_s=-1) == ["p1"]


def test_list_models_aggregates():
    reg = Registry()
    _add(reg, "p1", model="llama3:8b", maxc=10, conns=3)
    _add(reg, "p2", model="llama3:8b", maxc=10)
    _add(reg, "p3", model="mistral-7b")
    models = {m["model_name"]: m for m in reg.list_models()}
    assert models["llama3:8b"]["providers"] == 2
    assert models["llama3:8b"]["free_slots"] == 17


def test_session_tokens_offline_verification():
    server = Identity.from_name("srv")
    tok = tokens.mint(server, session_id="s1", client_key="c1",
                      model_name="llama3:8b", ttl_s=60)
    assert tokens.verify(tok, server.public_key) is not None
    assert tokens.verify(tok, server.public_key, client_key="c1",
                         model_name="llama3:8b") is not None
    # Wrong binding → rejected.
    assert tokens.verify(tok, server.public_key, client_key="other") is None
    assert tokens.verify(tok, server.public_key, model_name="other") is None
    # Wrong server key → rejected.
    assert tokens.verify(tok, Identity.from_name("fake").public_key) is None
    # Tampered payload → rejected.
    evil = {"payload": {**tok["payload"], "modelName": "gpt5"},
            "signature": tok["signature"]}
    assert tokens.verify(evil, server.public_key) is None
    # Expired → rejected.
    old = tokens.mint(server, session_id="s2", client_key="c1",
                      model_name="m", ttl_s=-1)
    assert tokens.verify(old, server.public_key) is None
    # Garbage shapes → rejected, no exception.
    for garbage in (None, "x", {}, {"payload": 1, "signature": "zz"},
                    {"payload": {}, "signature": "not-hex"}):
        assert tokens.verify(garbage, server.public_key) is None


def test_registry_migrates_pre_metrics_db(tmp_path):
    """A server DB created before the `metrics` column existed must be
    migrated in place (CREATE TABLE IF NOT EXISTS alone would leave it
    stale and break every provider row read)."""
    import sqlite3

    from symmetry_tpu.server.registry import Registry

    path = str(tmp_path / "old.db")
    db = sqlite3.connect(path)
    db.execute("""CREATE TABLE peers (
        peer_key TEXT PRIMARY KEY, discovery_key TEXT NOT NULL, name TEXT,
        model_name TEXT NOT NULL, address TEXT,
        public INTEGER NOT NULL DEFAULT 1, online INTEGER NOT NULL DEFAULT 1,
        connections INTEGER NOT NULL DEFAULT 0,
        max_connections INTEGER NOT NULL DEFAULT 10,
        data_collection INTEGER NOT NULL DEFAULT 0, config TEXT,
        joined_at REAL NOT NULL, last_seen REAL NOT NULL)""")
    db.execute("INSERT INTO peers VALUES "
               "('pk','dk','n','m','a',1,1,0,10,0,NULL,1.0,1.0)")
    db.commit()
    db.close()

    reg = Registry(path)
    reg.set_metrics("pk", {"tok_s": 5})
    row = reg.get_provider("pk")
    assert row is not None and row.metrics == {"tok_s": 5}
    reg.close()
