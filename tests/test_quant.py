"""Int8 weight quantization: op-level exactness bounds + model integration."""

import jax
import jax.numpy as jnp
import numpy as np

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset
from symmetry_tpu.models.llama import (
    param_logical_axes,
    quantize_params,
    quantized_logical_axes,
)
from symmetry_tpu.ops.quant import QuantizedTensor, dequantize, qmatmul, quantize


class TestQuantOps:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
        qt = quantize(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (32,)
        err = jnp.abs(dequantize(qt) - w)
        # Error per element bounded by half a quantization step per column.
        step = jnp.max(jnp.abs(w), axis=0) / 127.0
        assert bool(jnp.all(err <= 0.51 * step[None, :]))

    def test_qmatmul_matches_dequant_matmul(self):
        x = jax.random.normal(jax.random.key(1), (4, 64), jnp.float32)
        w = jax.random.normal(jax.random.key(2), (64, 32), jnp.float32)
        qt = quantize(w)
        got = qmatmul(x, qt)
        want = x @ dequantize(qt)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-3)

    def test_qmatmul_passthrough_dense(self):
        x = jnp.ones((2, 8))
        w = jnp.ones((8, 4))
        np.testing.assert_allclose(np.asarray(qmatmul(x, w)),
                                   np.asarray(x @ w))

    def test_stacked_layer_quantization(self):
        w = jax.random.normal(jax.random.key(3), (3, 16, 8), jnp.float32)
        qt = quantize(w)
        assert qt.q.shape == (3, 16, 8)
        assert qt.scale.shape == (3, 8)


class TestQuantModel:
    def test_quantized_forward_close_to_dense(self):
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 512, (1, 12)), jnp.int32)

        dense_logits, _ = forward(params, cfg, tokens,
                                  init_cache(cfg, 1, 16, jnp.float32))
        qparams = quantize_params(jax.tree.map(lambda a: a, params))
        q_logits, _ = forward(qparams, cfg, tokens,
                              init_cache(cfg, 1, 16, jnp.float32))
        # int8 noise is real but small; top-1 prediction must survive.
        np.testing.assert_allclose(np.asarray(q_logits),
                                   np.asarray(dense_logits),
                                   rtol=0.3, atol=0.3)
        agree = (np.argmax(np.asarray(q_logits), -1)
                 == np.argmax(np.asarray(dense_logits), -1)).mean()
        assert agree >= 0.8

    def test_quantized_logical_axes_structure(self):
        cfg = preset("tiny")
        axes = quantized_logical_axes(param_logical_axes(cfg))
        assert isinstance(axes["layers"]["wq"], QuantizedTensor)
        assert axes["layers"]["wq"].q == ("layers", "embed", "heads")
        assert axes["layers"]["wq"].scale == ("layers", "heads")
        assert axes["embed"] == ("vocab", "embed")

    def test_engine_runs_int8(self):
        cfg = preset("tiny")
        params = quantize_params(init_params(cfg, jax.random.key(0),
                                             jnp.float32))
        engine = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                                 max_seq_len=64, prefill_buckets=(16,),
                                 cache_dtype=jnp.float32)
        first = engine.prefill_and_insert(0, list(b"quantized"),
                                          SamplingParams())
        toks = engine.decode_step()
        assert toks.shape == (2,)
        assert 0 <= first < cfg.vocab_size


class TestKVQuant:
    """Int8 KV cache: ops/quant.py quantize_kv + the folded-dequant
    attention path (ops/attention.py k_scale/v_scale)."""

    def test_quantize_kv_roundtrip(self):
        from symmetry_tpu.ops.quant import quantize_kv

        x = jax.random.normal(jax.random.key(1), (2, 8, 4, 16), jnp.float32)
        q, scale = quantize_kv(x)
        assert q.dtype == jnp.int8
        assert scale.shape == (2, 8, 4)
        recon = q.astype(jnp.float32) * scale[..., None]
        err = np.abs(np.asarray(recon - x))
        # symmetric per-(token, head) quant: error <= scale/2 per element
        assert (err <= np.asarray(scale)[..., None] / 2 + 1e-6).all()

    def test_folded_dequant_attention_exact(self):
        """The folded-scale path (int8 cache + k_scale/v_scale) must equal
        attention over an explicitly dequantized cache — the algebra is
        exact, so this isolates the wiring from quantization noise."""
        from symmetry_tpu.ops.attention import gqa_attention
        from symmetry_tpu.ops.quant import quantize_kv

        B, S, T, nq, nkv, D = 2, 3, 16, 4, 2, 8
        ks = jax.random.split(jax.random.key(2), 3)
        q = jax.random.normal(ks[0], (B, S, nq, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, T, nkv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, T, nkv, D), jnp.float32)
        kq, k_sc = quantize_kv(k)   # scales [B, T, K]
        vq, v_sc = quantize_kv(v)
        k_deq = kq.astype(jnp.float32) * k_sc[..., None]
        v_deq = vq.astype(jnp.float32) * v_sc[..., None]

        positions = jnp.broadcast_to(
            jnp.arange(8, 8 + S, dtype=jnp.int32)[None], (B, S))
        kv_len = jnp.full((B,), 8 + S, jnp.int32)

        # attention takes scales position-minor: [B, K, T]
        folded = gqa_attention(q, kq, vq, positions, kv_len,
                               k_scale=jnp.moveaxis(k_sc, 1, 2),
                               v_scale=jnp.moveaxis(v_sc, 1, 2))
        explicit = gqa_attention(q, k_deq, v_deq, positions, kv_len)
        np.testing.assert_allclose(np.asarray(folded), np.asarray(explicit),
                                   rtol=1e-5, atol=1e-5)

    def test_quantized_cache_forward_close_to_dense(self):
        """Same prompt through a dense cache vs an int8 cache: logits must
        agree to within the per-token quant noise bound (the random-init
        model's logit gaps are smaller than that, so no argmax check)."""
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        rng = np.random.default_rng(1)
        prompt = jnp.asarray(rng.integers(0, 512, (1, 12)), jnp.int32)

        l_d, _ = forward(params, cfg, prompt,
                         init_cache(cfg, 1, 32, jnp.float32))
        l_q, _ = forward(params, cfg, prompt,
                         init_cache(cfg, 1, 32, jnp.float32, quantized=True))
        d, q = np.asarray(l_d[:, -1]), np.asarray(l_q[:, -1])
        scale = np.abs(d).max()
        assert np.abs(d - q).max() <= 0.05 * scale

    def test_engine_kv_quant_decodes(self):
        """Engine end-to-end with an int8 cache: prefill → insert → decode
        across two interleaved slots, valid tokens throughout."""
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        eng = InferenceEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            prefill_buckets=(16,), cache_dtype=jnp.float32, kv_quant=True)
        first0 = eng.prefill_and_insert(0, list(b"kv quant test"),
                                        SamplingParams())
        eng.decode_step()
        first1 = eng.prefill_and_insert(1, list(b"another prompt"),
                                        SamplingParams())
        for _ in range(6):
            toks = eng.decode_step()
            assert toks.shape == (2,)
            assert (0 <= toks).all() and (toks < cfg.vocab_size).all()
        assert 0 <= first0 < cfg.vocab_size
        assert 0 <= first1 < cfg.vocab_size
        # slot 0: 13-token prompt + 7 decode writes; slot 1: 14 + 6
        assert eng.slot_length(0) == len(b"kv quant test") + 7
        assert eng.slot_length(1) == len(b"another prompt") + 6
