"""Int8 weight quantization: op-level exactness bounds + model integration."""

import jax
import jax.numpy as jnp
import numpy as np

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.tokenizer import ByteTokenizer
from symmetry_tpu.models import forward, init_cache, init_params, preset
from symmetry_tpu.models.llama import (
    param_logical_axes,
    quantize_params,
    quantized_logical_axes,
)
from symmetry_tpu.ops.quant import QuantizedTensor, dequantize, qmatmul, quantize


class TestQuantOps:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
        qt = quantize(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (32,)
        err = jnp.abs(dequantize(qt) - w)
        # Error per element bounded by half a quantization step per column.
        step = jnp.max(jnp.abs(w), axis=0) / 127.0
        assert bool(jnp.all(err <= 0.51 * step[None, :]))

    def test_qmatmul_matches_dequant_matmul(self):
        x = jax.random.normal(jax.random.key(1), (4, 64), jnp.float32)
        w = jax.random.normal(jax.random.key(2), (64, 32), jnp.float32)
        qt = quantize(w)
        got = qmatmul(x, qt)
        want = x @ dequantize(qt)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-3)

    def test_qmatmul_passthrough_dense(self):
        x = jnp.ones((2, 8))
        w = jnp.ones((8, 4))
        np.testing.assert_allclose(np.asarray(qmatmul(x, w)),
                                   np.asarray(x @ w))

    def test_stacked_layer_quantization(self):
        w = jax.random.normal(jax.random.key(3), (3, 16, 8), jnp.float32)
        qt = quantize(w)
        assert qt.q.shape == (3, 16, 8)
        assert qt.scale.shape == (3, 8)


class TestQuantModel:
    def test_quantized_forward_close_to_dense(self):
        cfg = preset("tiny")
        params = init_params(cfg, jax.random.key(0), jnp.float32)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 512, (1, 12)), jnp.int32)

        dense_logits, _ = forward(params, cfg, tokens,
                                  init_cache(cfg, 1, 16, jnp.float32))
        qparams = quantize_params(jax.tree.map(lambda a: a, params))
        q_logits, _ = forward(qparams, cfg, tokens,
                              init_cache(cfg, 1, 16, jnp.float32))
        # int8 noise is real but small; top-1 prediction must survive.
        np.testing.assert_allclose(np.asarray(q_logits),
                                   np.asarray(dense_logits),
                                   rtol=0.3, atol=0.3)
        agree = (np.argmax(np.asarray(q_logits), -1)
                 == np.argmax(np.asarray(dense_logits), -1)).mean()
        assert agree >= 0.8

    def test_quantized_logical_axes_structure(self):
        cfg = preset("tiny")
        axes = quantized_logical_axes(param_logical_axes(cfg))
        assert isinstance(axes["layers"]["wq"], QuantizedTensor)
        assert axes["layers"]["wq"].q == ("layers", "embed", "heads")
        assert axes["layers"]["wq"].scale == ("layers", "heads")
        assert axes["embed"] == ("vocab", "embed")

    def test_engine_runs_int8(self):
        cfg = preset("tiny")
        params = quantize_params(init_params(cfg, jax.random.key(0),
                                             jnp.float32))
        engine = InferenceEngine(cfg, params, ByteTokenizer(), max_slots=2,
                                 max_seq_len=64, prefill_buckets=(16,),
                                 cache_dtype=jnp.float32)
        first = engine.prefill_and_insert(0, list(b"quantized"),
                                          SamplingParams())
        toks = engine.decode_step()
        assert toks.shape == (2,)
        assert 0 <= first < cfg.vocab_size
